"""Per-op microbenchmark harness — analog of the reference's op_tester
(paddle/fluid/operators/benchmark/op_tester.cc) + ci benchmark gate.

Times a fixed suite of core ops as jitted XLA programs on the current
backend (the real TPU chip under axon; CPU elsewhere), prints one JSON
line per op, and can gate regressions against a stored baseline:

    python bench_ops.py                         # run + print
    python bench_ops.py --save OPBENCH.json     # record baseline
    python bench_ops.py --check OPBENCH.json    # exit 1 on >25% regress
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np


def _timeit(fn, *args, n_small=8, target_s=0.4, n_cap=1 << 15):
    """Tunnel-proof timing. Per-dispatch timing is useless over the axon
    TPU tunnel: dispatch latency dominates (~100ms with ±ms jitter),
    async completion is opaque to block_until_ready, and repeat
    dispatches of the same executable on the same buffers can be served
    memoized (~0 ms). So each measurement runs N iterations of the op
    INSIDE one program — a lax.fori_loop with N as a DYNAMIC argument,
    so one compilation serves every N (inputs salted per-iteration so
    nothing is loop-invariant, outputs folded into a scalar carry so
    every iteration is on the data path), forced by a 4-byte host read.
    N grows adaptively until the in-loop time rises far above the
    dispatch jitter (>= target_s), then the slope between N_small and
    N_big cancels the fixed overhead. Micro-ops (tens of us) need
    thousands of iterations to clear the noise floor — a static-N scan
    would recompile per N (~30s per shape over the tunnel remote
    compiler), which is why the loop bound must be dynamic."""

    def salted(a, s):
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.inexact):
            return a + s.astype(a.dtype)
        return a

    def scalarize(out):
        leaves = jax.tree_util.tree_leaves(out)
        return sum(jnp.sum(l).astype(jnp.float32) for l in leaves
                   if hasattr(l, "dtype") and
                   jnp.issubdtype(l.dtype, jnp.inexact))

    @jax.jit
    def many(salt, args, n):
        def body(i, c):
            varied = tuple(salted(a, (i.astype(jnp.float32) + salt))
                           for a in args)
            return c + scalarize(fn(*varied))
        return jax.lax.fori_loop(0, n, body, jnp.float32(0.0))

    def run_once(salt, n):
        t0 = time.perf_counter()
        float(many(jnp.float32(salt), args, jnp.int32(n)))
        return time.perf_counter() - t0

    salt = [0.0]

    def best(n, reps=3):
        ts = []
        for _ in range(reps):
            salt[0] += 1.0
            ts.append(run_once(salt[0], n))
        return min(ts)

    best(n_small, reps=1)  # compile (one program serves every n)
    n_big = max(4 * n_small, 128)
    while n_big < n_cap and best(n_big, reps=1) < target_s:
        n_big *= 2
    t_small, t_big = best(n_small), best(n_big)
    slope = (t_big - t_small) / (n_big - n_small)
    if slope <= 0:  # below the noise floor even at n_cap
        slope = t_big / n_big
    return slope * 1e3  # ms


def _rand(shape, dtype=jnp.bfloat16, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32) \
        .astype(dtype)


# every suite() row, in order — kept literal so tooling that only needs
# the NAMES (check_bench_result --pending) doesn't pay suite()'s eager
# input allocation + backend init; test_engine_offered_load_bench_
# runner_tiny asserts it matches suite() exactly, so it cannot drift
SUITE_ROWS = (
    "matmul_4096_bf16", "conv2d_7x7_s2",
    "conv_c2_1x1_64_256", "conv_c2_3x3_64", "conv_c3_3x3_128_s2",
    "conv_c3_3x3_128", "conv_c4_3x3_256_s2", "conv_c4_3x3_256",
    "conv_c5_3x3_512_s2", "conv_c5_3x3_512", "conv_c5_1x1_512_2048",
    "flash_attention_2k", "layernorm_2048", "softmax_xent_50k",
    "embedding_50k", "reduce_sum_64M", "gpt_decode_kv_32tok",
    "gpt_decode_kv_350m", "gpt_engine_offered_load",
    "paged_attention_decode_sweep", "gpt_engine_offered_load_pallas",
    "gpt_engine_prefix_cache", "gpt_engine_chunked_prefill",
    "gpt_engine_speculative", "gpt_engine_offered_load_mp2",
    "gpt_engine_offered_load_int8", "gpt_fleet_offered_load",
    "gpt_engine_multitenant_lora", "gpt_engine_sampling",
    "conv_fused_sweep", "resnet50_fused_block",
    "conv_fused_bwd_sweep", "resnet50_fused_block_train",
    "gpt_engine_host_gap", "gpt_engine_async_overlap",
)


def suite_names():
    """Row names without building any case (cheap to import + call)."""
    return list(SUITE_ROWS)


def suite():
    """name -> (fn, args, flops-or-None). Shapes sized for one chip."""
    import paddle_tpu  # noqa: F401  (registers pallas kernels)
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    B, S, H, D = 4, 2048, 16, 128
    M = 4096
    cases = {}

    x = _rand((M, M))
    w = _rand((M, M), seed=1)
    cases["matmul_4096_bf16"] = (
        jax.jit(lambda a, b: a @ b), (x, w), 2 * M ** 3)

    img = _rand((32, 224, 224, 3))
    ker = _rand((7, 7, 3, 64), seed=2)
    cases["conv2d_7x7_s2"] = (
        jax.jit(lambda i, k: jax.lax.conv_general_dilated(
            i, k, (2, 2), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))),
        (img, ker),
        2 * 32 * 112 * 112 * 64 * 7 * 7 * 3)

    # ResNet-50 conv-shape sweep (VERDICT r3 weak #2): every distinct
    # (kernel, stride, width, resolution) class in the network, batch 32.
    # This is the evidence base for the "conv ceiling" reading of the
    # resnet50 bench row: if any of these clears well above ~43 TF/s the
    # stem/stage strategy should be revisited. Reference analog:
    # paddle/fluid/operators/benchmark/op_tester.cc config sweeps.
    def conv_case(name, n, hw, cin, cout, k, s):
        i = _rand((n, hw, hw, cin))
        # crc32, not hash(): str hash is randomized per process and
        # would make the sweep's inputs differ run-to-run
        w = _rand((k, k, cin, cout), seed=zlib.crc32(name.encode()) % 97)
        ho = hw // s
        cases[name] = (
            jax.jit(lambda a, b: jax.lax.conv_general_dilated(
                a, b, (s, s), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))),
            (i, w), 2 * n * ho * ho * cout * k * k * cin)

    conv_case("conv_c2_1x1_64_256", 32, 56, 64, 256, 1, 1)
    conv_case("conv_c2_3x3_64", 32, 56, 64, 64, 3, 1)
    conv_case("conv_c3_3x3_128_s2", 32, 56, 128, 128, 3, 2)
    conv_case("conv_c3_3x3_128", 32, 28, 128, 128, 3, 1)
    conv_case("conv_c4_3x3_256_s2", 32, 28, 256, 256, 3, 2)
    conv_case("conv_c4_3x3_256", 32, 14, 256, 256, 3, 1)
    conv_case("conv_c5_3x3_512_s2", 32, 14, 512, 512, 3, 2)
    conv_case("conv_c5_3x3_512", 32, 7, 512, 512, 3, 1)
    conv_case("conv_c5_1x1_512_2048", 32, 7, 512, 2048, 1, 1)

    q = _rand((B, S, H, D))
    k = _rand((B, S, H, D), seed=3)
    v = _rand((B, S, H, D), seed=4)
    cases["flash_attention_2k"] = (
        jax.jit(lambda a, b, c: flash_attention(a, b, c, causal=True)),
        (q, k, v), 4 * B * H * S * S * D // 2)

    h = _rand((B * S, M // 2))
    g = _rand((M // 2,))
    b2 = _rand((M // 2,), seed=5)
    cases["layernorm_2048"] = (
        jax.jit(lambda a, gg, bb: (a - a.mean(-1, keepdims=True))
                / jnp.sqrt(a.var(-1, keepdims=True) + 1e-5) * gg + bb),
        (h, g, b2), None)

    logits = _rand((2048, 50304), jnp.float32)
    cases["softmax_xent_50k"] = (
        jax.jit(lambda lg: -jax.nn.log_softmax(lg)[:, 0].mean()),
        (logits,), None)

    tbl = _rand((50304, 2048))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 50304, B * S))
    cases["embedding_50k"] = (
        jax.jit(lambda t, i: t[i]), (tbl, ids), None)

    big = _rand((64, 1 << 20))
    cases["reduce_sum_64M"] = (
        jax.jit(lambda a: a.astype(jnp.float32).sum()), (big,), None)

    cases["gpt_decode_kv_32tok"] = _decode_case()
    # heavy inference rows build lazily: suite() stays cheap to enumerate
    # (CPU CI imports it), run() resolves the callables when measuring
    cases["gpt_decode_kv_350m"] = _decode_350m_case
    cases["gpt_engine_offered_load"] = _engine_offered_load_case()
    cases["paged_attention_decode_sweep"] = _paged_attention_sweep_case()
    cases["gpt_engine_offered_load_pallas"] = _engine_offered_load_case(
        attention_backend="pallas")
    cases["gpt_engine_prefix_cache"] = _engine_prefix_cache_case()
    cases["gpt_engine_chunked_prefill"] = _engine_chunked_prefill_case()
    cases["gpt_engine_speculative"] = _engine_speculative_case()
    cases["gpt_engine_offered_load_mp2"] = _engine_offered_load_case(
        mp_degree=2)
    cases["gpt_engine_offered_load_int8"] = _engine_offered_load_case(
        kv_dtype="int8")
    cases["gpt_fleet_offered_load"] = _fleet_offered_load_case()
    cases["gpt_engine_multitenant_lora"] = \
        _engine_multitenant_lora_case()
    cases["gpt_engine_sampling"] = _engine_sampling_case()
    cases["conv_fused_sweep"] = _conv_fused_sweep_case()
    cases["resnet50_fused_block"] = _resnet50_fused_block_case()
    cases["conv_fused_bwd_sweep"] = _conv_fused_bwd_sweep_case()
    cases["resnet50_fused_block_train"] = \
        _resnet50_fused_block_train_case()
    cases["gpt_engine_host_gap"] = _engine_host_gap_case()
    cases["gpt_engine_async_overlap"] = _engine_async_overlap_case()
    # every suite() caller trips on drift immediately, not just the one
    # CI test — SUITE_ROWS must stay the cheap names-only mirror
    assert tuple(cases) == SUITE_ROWS, \
        "bench_ops.SUITE_ROWS is out of sync with suite(); update it"
    return cases


def _decode_case():
    """KV-cache greedy-decode throughput (VERDICT r4 next #8): a small
    GPT config (~21M params — the 1.3B cached-decode program takes
    >10 min through the remote compiler, so the tracked number lives
    here) decoding 32 new tokens per call through the SAME compiled
    fixed-buffer lax.while_loop path the big model uses
    (models/gpt.py _generate_cached). The fn takes a FLOAT fuzz input
    (so _timeit's per-iteration salting varies the prompt — int inputs
    aren't salted and XLA would hoist a constant decode out of the
    timing loop) and returns the tokens as float (so they land in the
    scalarized carry). rec extra: tokens per call for tokens/s."""
    import paddle_tpu  # noqa: F401  (registers ops)
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    B, S0, L, vocab = 4, 16, 48, 4096
    cfg = GPTConfig(vocab_size=vocab, hidden_size=512, num_layers=6,
                    num_heads=8, max_seq_len=L)
    model = GPTForCausalLM(cfg)
    model.eval()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    new_tokens = L - S0

    def decode(fuzz):
        ids = (jnp.abs(fuzz).astype(jnp.int32) % vocab)
        toks = model.generate(Tensor._wrap(ids), max_length=L,
                              use_cache=True)
        return toks._array.astype(jnp.float32)

    fuzz = jnp.abs(_rand((B, S0), jnp.float32, seed=11)) * 997.0
    flops = 2 * n_params * B * new_tokens  # matmul-dominated decode
    return (decode, (fuzz,), flops, {"tokens": B * new_tokens})


def _decode_350m_case():
    """The VERDICT r5 next-#9 representative decode row: GPT-medium
    (~350M params — the published GPT-2-medium shape) decoding 256 new
    tokens per call for a batch of 8 through the compiled fixed-buffer
    lax.while_loop KV-cache path, timed inside _timeit's dynamic-N
    fori_loop like every other row. Supersedes the 21M 32-token toy as
    the single-program decode health number (the toy stays for cheap
    CPU coverage of the code path). Same float-fuzz prompt trick as
    _decode_case so nothing is loop-invariant."""
    import numpy as np

    import paddle_tpu  # noqa: F401  (registers ops)
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    B, S0, L, vocab = 8, 128, 384, 50304
    cfg = GPTConfig(vocab_size=vocab, hidden_size=1024, num_layers=24,
                    num_heads=16, max_seq_len=L)
    model = GPTForCausalLM(cfg)
    model.eval()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    new_tokens = L - S0

    def decode(fuzz):
        ids = (jnp.abs(fuzz).astype(jnp.int32) % vocab)
        toks = model.generate(Tensor._wrap(ids), max_length=L,
                              use_cache=True)
        return toks._array.astype(jnp.float32)

    fuzz = jnp.abs(_rand((B, S0), jnp.float32, seed=13)) * 9973.0
    flops = 2 * n_params * B * new_tokens
    return (decode, (fuzz,), flops, {"tokens": B * new_tokens})


def _paged_attention_sweep_case(num_slots=8, heads=16, head_dim=128,
                                block_size=16, max_model_len=2048,
                                ctx_lengths=(128, 512, 2048),
                                backends=("dense", "pallas"),
                                dtype=None, seed=17):
    """ISSUE-3 paged-attention microbench: one decode-attention step
    (fused KV write + attention over the slot's cached context) at a
    FIXED max_model_len while the ACTIVE context sweeps `ctx_lengths`,
    timed per backend. The sweep is the O(active-context) evidence the
    tentpole claims: the dense fallback's per-step time must track the
    active-context high-water mark (its fori_loop trip count), not sit
    flat at the max_model_len cost PR 1's full-table gather paid, and
    the pallas kernel must track it with a lower slope (per-slot
    block streaming instead of a batch gather). Headline `ms` is the
    pallas full-context time — the fused kernel is what this row
    tracks; the per-backend curves ride in the record. The int8
    curves (`<backend>_int8_ms_by_ctx`, PR 11) run the SAME sweep
    against int8 per-block-quantized pools + scales — the
    streamed-bytes halving the quantized KV cache claims, visible as
    a flatter dense slope and a cheaper pallas walk on TPU.
    Lazy-built like every heavy inference row; tests call it at a
    tiny shape (pallas runs interpreted off-TPU)."""

    def run_bench():
        import paddle_tpu  # noqa: F401  (registers ops)
        from paddle_tpu.ops.paged_attention import (KV_QUANT_EPS,
                                                    paged_attention_step)

        dt = dtype or jnp.bfloat16
        max_blocks = max(max_model_len // block_size, 1)
        num_blocks = 1 + num_slots * max_blocks
        L = 1                            # one layer plane: the op cost
        kpool = _rand((L, num_blocks, block_size, heads, head_dim), dt,
                      seed=seed)
        vpool = _rand((L, num_blocks, block_size, heads, head_dim), dt,
                      seed=seed + 1)

        def quantize_pool(pool):
            arr = pool.astype(jnp.float32)
            s = jnp.maximum(
                jnp.max(jnp.abs(arr), axis=(2, 3, 4)) / 127.0,
                KV_QUANT_EPS)                        # [L, blocks]
            q = jnp.clip(jnp.round(arr / s[:, :, None, None, None]),
                         -127, 127).astype(jnp.int8)
            return q, s

        kq, ks = quantize_pool(kpool)
        vq, vs = quantize_pool(vpool)
        kpool_q, vpool_q = kq, vq
        scales_q = jnp.stack([ks, vs], axis=-1)      # [L, blocks, 2]
        # disjoint per-slot tables covering the whole budget; the sweep
        # only moves `positions`, so every backend sees the same layout
        tables = 1 + np.arange(num_slots * max_blocks, dtype=np.int32) \
            .reshape(num_slots, max_blocks)
        q = _rand((num_slots, 1, heads, head_dim), dt, seed=seed + 2)
        k_new = _rand((num_slots, 1, heads, head_dim), dt, seed=seed + 3)
        v_new = _rand((num_slots, 1, heads, head_dim), dt, seed=seed + 4)

        curves = {b: {} for b in backends}
        curves_q = {b: {} for b in backends}
        for ctx in ctx_lengths:
            positions = np.full(num_slots, ctx - 1, np.int32)
            for b in backends:
                # pools ride in the closure (the _decode_350m_case
                # idiom), NOT as _timeit args: salting them would add
                # an O(pool-size) element-wise pass per iteration that
                # swamps the O(active-context) attention traffic this
                # row exists to expose; q/k/v salting alone keeps the
                # step off the loop-invariant path
                def step(qa, ka, va, _b=b, _pos=positions):
                    out, _, _ = paged_attention_step(
                        qa, ka, va, kpool, vpool, 0, tables, _pos,
                        backend=_b)
                    return out._array
                ms = _timeit(step, q, k_new, v_new)
                curves[b][str(ctx)] = round(ms, 4)

                def step_q(qa, ka, va, _b=b, _pos=positions):
                    out, _, _, _ = paged_attention_step(
                        qa, ka, va, kpool_q, vpool_q, 0, tables,
                        _pos, backend=_b, scales=scales_q)
                    return out._array
                ms = _timeit(step_q, q, k_new, v_new)
                curves_q[b][str(ctx)] = round(ms, 4)
        head = "pallas" if "pallas" in curves else backends[0]
        rec = {"ms": curves[head][str(ctx_lengths[-1])],
               "max_model_len": max_model_len,
               "block_size": block_size}
        for b in backends:
            rec[f"{b}_ms_by_ctx"] = curves[b]
            rec[f"{b}_int8_ms_by_ctx"] = curves_q[b]
        return rec

    return run_bench


#: The nine ResNet-50 sweep shapes (name, hw, cin, cout, k, s) the
#: conv_case rows above measure through lax.conv_general_dilated —
#: the fused-vs-dense row runs the SAME geometry through both paths.
CONV_SWEEP_SHAPES = (
    ("conv_c2_1x1_64_256", 56, 64, 256, 1, 1),
    ("conv_c2_3x3_64", 56, 64, 64, 3, 1),
    ("conv_c3_3x3_128_s2", 56, 128, 128, 3, 2),
    ("conv_c3_3x3_128", 28, 128, 128, 3, 1),
    ("conv_c4_3x3_256_s2", 28, 256, 256, 3, 2),
    ("conv_c4_3x3_256", 14, 256, 256, 3, 1),
    ("conv_c5_3x3_512_s2", 14, 512, 512, 3, 2),
    ("conv_c5_3x3_512", 7, 512, 512, 3, 1),
    ("conv_c5_1x1_512_2048", 7, 512, 2048, 1, 1),
)

#: Documented numeric budget for the fused conv suite (ISSUE 14): the
#: fused Pallas conv+BN+ReLU output must agree with the dense
#: lax.conv_general_dilated composition within this relative-Linf
#: tolerance at bf16 inputs (both paths accumulate fp32 and cast
#: once; only reduction order differs). README "Pallas conv suite"
#: states the policy; tests/test_pallas_conv.py enforces it per
#: sweep shape, fp32 at a far tighter bound.
CONV_FUSED_REL_TOL = 0.03


def _conv_rel_err(got, ref):
    import jax.numpy as jnp

    g = jnp.asarray(got, jnp.float32)
    r = jnp.asarray(ref, jnp.float32)
    denom = jnp.maximum(jnp.max(jnp.abs(r)), 1e-6)
    return float(jnp.max(jnp.abs(g - r)) / denom)


def _conv_rel_err_l2(got, ref):
    """Relative L2 error — the GRADIENT metric: bf16 rounding feeds
    sign-cancelling sums in dInput/dWeight, so per-element Linf
    deviations run ~10x the aggregate error for BOTH the fused and
    the dense backward (each sits the same L2 distance from the fp32
    truth; DESIGN_DECISIONS r19). The Linf metric stays the forward
    budget, where no cancellation exists."""
    import jax.numpy as jnp

    g = jnp.asarray(got, jnp.float32)
    r = jnp.asarray(ref, jnp.float32)
    denom = jnp.maximum(jnp.linalg.norm(r), 1e-6)
    return float(jnp.linalg.norm(g - r) / denom)


def _conv_fused_sweep_case(shapes=None, batch=32, dtype=None,
                           seed=23):
    """ISSUE-14 fused-conv microbench: every ResNet sweep shape run
    through BOTH paths — the dense `lax.conv_general_dilated` + BN
    scale/shift + ReLU composition (one jitted program: XLA's best
    fusion, the r5 probe's ceiling) and the fused Pallas kernel
    (`ops/pallas/conv.py`, interpret off-TPU) — with the outputs
    tolerance-asserted in-runner before anything is timed. The per-
    shape dense/fused ms + TFLOP/s curves are the evidence the
    tentpole claims: on TPU the fused kernels must close the 24-76 vs
    184 TFLOP/s matmul gap the sweep rows above measure. Headline
    `ms` is the fused time of the worst matmul-gap row
    (conv_c2_1x1_64_256). Lazy-built; tests call it at tiny shapes
    (the interpreter is the off-TPU path)."""

    def run_bench():
        import paddle_tpu  # noqa: F401  (registers pallas kernels)
        from paddle_tpu.ops.pallas.conv import (_on_tpu,
                                                conv_bn_relu_reference,
                                                fused_conv_bn_relu)

        if os.environ.get("PADDLE_CONV_BACKEND"):
            # the row compares the two paths by name; an env override
            # rerouting either side would record a lie under it
            raise RuntimeError(
                "unset PADDLE_CONV_BACKEND to run the fused-vs-dense "
                "sweep")
        dt = dtype or jnp.bfloat16
        interpret = not _on_tpu()
        rows = shapes or CONV_SWEEP_SHAPES
        curves, head_ms = {}, None
        for name, hw, cin, cout, k, s in rows:
            x = _rand((batch, hw, hw, cin), dt,
                      seed=zlib.crc32(name.encode()) % 89 + seed)
            w = _rand((k, k, cin, cout), dt, seed=seed + 1) * 0.1
            scale = jnp.abs(_rand((cout,), jnp.float32, seed=seed + 2)) \
                + 0.5
            shift = _rand((cout,), jnp.float32, seed=seed + 3)

            dense = jax.jit(lambda a, b, sc, sh, _s=s:
                            conv_bn_relu_reference(a, b, sc, sh,
                                                   stride=_s,
                                                   padding="SAME"))
            fused = jax.jit(lambda a, b, sc, sh, _s=s:
                            fused_conv_bn_relu(a, b, sc, sh, stride=_s,
                                               padding="SAME",
                                               interpret=interpret))
            err = _conv_rel_err(fused(x, w, scale, shift),
                                dense(x, w, scale, shift))
            assert err <= CONV_FUSED_REL_TOL, \
                (f"{name}: fused output diverges from the dense "
                 f"composition (rel err {err:.4f}, budget "
                 f"{CONV_FUSED_REL_TOL})")
            dense_ms = _timeit(dense, x, w, scale, shift)
            fused_ms = _timeit(fused, x, w, scale, shift)
            ho = hw // s
            flops = 2 * batch * ho * ho * cout * k * k * cin
            curves[name] = {
                "dense_ms": round(dense_ms, 4),
                "fused_ms": round(fused_ms, 4),
                "dense_tflops": round(flops / (dense_ms / 1e3) / 1e12,
                                      2),
                "fused_tflops": round(flops / (fused_ms / 1e3) / 1e12,
                                      2),
                "rel_err": round(err, 5)}
            if head_ms is None or name == "conv_c2_1x1_64_256":
                head_ms = fused_ms
        return {"ms": round(head_ms, 4), "batch": batch,
                "shapes": curves}

    return run_bench


def _resnet50_fused_block_case(batch=32, hw=56, inplanes=256,
                               planes=64, dtype="bfloat16", seed=29):
    """ISSUE-14 block-level row: one ResNet-50 stage-2 BottleneckBlock
    (1x1 256->64, 3x3 64->64, 1x1 64->256 + residual) served in eval
    mode through BOTH conv backends — `pallas` (every conv+BN+ReLU one
    fused kernel) and `dense` (today's composition, the exactness
    foil) — outputs tolerance-asserted in-runner, both forward times
    recorded. This is the end-to-end shape the MFU plateau lives in:
    three bandwidth-bound convs whose BN/ReLU re-reads the fused path
    deletes. Off-TPU the kernels run interpreted (structure only);
    the TPU refresh gives the measured speedup."""

    def run_bench():
        import paddle_tpu as paddle
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.vision.models.resnet import BottleneckBlock

        if os.environ.get("PADDLE_CONV_BACKEND"):
            raise RuntimeError(
                "unset PADDLE_CONV_BACKEND to run the fused-vs-dense "
                "block row")

        def build(backend):
            paddle.seed(seed)            # identical weights per build
            blk = BottleneckBlock(inplanes, planes,
                                  conv_backend=backend)
            if dtype == "bfloat16":
                blk.to(dtype="bfloat16")
            blk.eval()
            return blk

        x = _rand((batch, inplanes, hw, hw),
                  jnp.bfloat16 if dtype == "bfloat16" else jnp.float32,
                  seed=seed)

        def timed(blk):
            fn = jax.jit(lambda a: blk(Tensor._wrap(a))._array)
            out = fn(x)
            return out, _timeit(fn, x)

        out_d, dense_ms = timed(build("dense"))
        out_p, fused_ms = timed(build("pallas"))
        err = _conv_rel_err(out_p, out_d)
        assert err <= CONV_FUSED_REL_TOL, \
            (f"fused block diverges from dense (rel err {err:.4f}, "
             f"budget {CONV_FUSED_REL_TOL})")
        width = planes
        flops = 2 * batch * hw * hw * (
            inplanes * width + width * width * 9 + width * inplanes)
        return {"ms": round(fused_ms, 4),
                "dense_ms": round(dense_ms, 4),
                "speedup_vs_dense": round(dense_ms / fused_ms, 3),
                "tflops": round(flops / (fused_ms / 1e3) / 1e12, 2),
                "rel_err": round(err, 5),
                "batch": batch, "hw": hw}

    return run_bench


def _conv_fused_bwd_sweep_case(shapes=None, batch=32, dtype=None,
                               seed=41):
    """ISSUE-16 backward microbench: every ResNet sweep shape's full
    train-mode grad program — forward + dInput + dWeight + BN-param
    grads — run through BOTH paths: `jax.vjp` of the dense
    differentiable composition (`conv_bn_relu_train_reference`, XLA's
    best training-graph fusion, the ~0.20-MFU ceiling of the r5
    probe) and the fused `custom_vjp` op (`fused_conv_bn_relu_train`:
    stats-in-epilogue forward, two-pass Pallas backward). All four
    gradients are tolerance-asserted in-runner before timing. FLOPs
    count the three convolutions a grad step performs (fwd, dX, dW).
    Headline `ms` is the fused grad time of the worst matmul-gap row
    (conv_c2_1x1_64_256). Lazy-built; tests call it at tiny shapes
    (the interpreter is the off-TPU path)."""

    def run_bench():
        import paddle_tpu  # noqa: F401  (registers pallas kernels)
        from paddle_tpu.ops.pallas.conv import (
            _on_tpu, conv_bn_relu_train_reference,
            fused_conv_bn_relu_train)

        if os.environ.get("PADDLE_CONV_BACKEND"):
            raise RuntimeError(
                "unset PADDLE_CONV_BACKEND to run the fused-vs-dense "
                "bwd sweep")
        dt = dtype or jnp.bfloat16
        interpret = not _on_tpu()
        rows = shapes or CONV_SWEEP_SHAPES
        curves, head_ms = {}, None
        for name, hw, cin, cout, k, s in rows:
            x = _rand((batch, hw, hw, cin), dt,
                      seed=zlib.crc32(name.encode()) % 83 + seed)
            w = _rand((k, k, cin, cout), dt, seed=seed + 1) * 0.1
            gamma = jnp.abs(_rand((cout,), jnp.float32,
                                  seed=seed + 2)) + 0.5
            beta = _rand((cout,), jnp.float32, seed=seed + 3)
            ho = hw // s
            # both paths emit the fp32-affine output dtype, so the
            # incoming cotangent is fp32 for either
            dy = _rand((batch, ho, ho, cout), jnp.float32,
                       seed=seed + 4)

            def make_grads(fn):
                def run(a, b, g2, b2, ct):
                    _, vjp = jax.vjp(lambda *ar: fn(*ar)[0],
                                     a, b, g2, b2)
                    return vjp(ct)
                return jax.jit(run)

            dense = make_grads(
                lambda a, b, g2, b2, _s=s: conv_bn_relu_train_reference(
                    a, b, g2, b2, stride=_s, padding="SAME"))
            fused = make_grads(
                lambda a, b, g2, b2, _s=s: fused_conv_bn_relu_train(
                    a, b, g2, b2, stride=_s, padding="SAME",
                    interpret=interpret))
            ref = dense(x, w, gamma, beta, dy)
            got = fused(x, w, gamma, beta, dy)
            err = max(_conv_rel_err_l2(g, r)
                      for g, r in zip(got, ref))
            assert err <= CONV_FUSED_REL_TOL, \
                (f"{name}: fused gradients diverge from the dense "
                 f"composition (rel err {err:.4f}, budget "
                 f"{CONV_FUSED_REL_TOL})")
            dense_ms = _timeit(dense, x, w, gamma, beta, dy)
            fused_ms = _timeit(fused, x, w, gamma, beta, dy)
            flops = 3 * 2 * batch * ho * ho * cout * k * k * cin
            curves[name] = {
                "dense_ms": round(dense_ms, 4),
                "fused_ms": round(fused_ms, 4),
                "dense_tflops": round(flops / (dense_ms / 1e3) / 1e12,
                                      2),
                "fused_tflops": round(flops / (fused_ms / 1e3) / 1e12,
                                      2),
                "rel_err": round(err, 5)}
            if head_ms is None or name == "conv_c2_1x1_64_256":
                head_ms = fused_ms
        return {"ms": round(head_ms, 4), "batch": batch,
                "shapes": curves}

    return run_bench


def _resnet50_fused_block_train_case(batch=32, hw=56, inplanes=256,
                                     planes=64, seed=43, steps=10):
    """ISSUE-16 block-level training row: one ResNet-50 stage-2
    BottleneckBlock run through a full compiled `jit.TrainStep`
    (fwd + bwd + SGD update, one donated XLA program) with
    `conv_backend='dense'` (today's training composition — the
    hbm-roofline wall BENCH_r05 measured at 0.152 MFU) and
    `conv_backend='pallas'` (all four conv+BN+ReLU stacks through the
    fused custom_vjp, forward AND backward). Losses after one
    identical-weights step are tolerance-asserted before timing; both
    per-step times are recorded. This is the row structured to show
    training moving past the ~0.20 fusion ceiling on the next TPU
    `--save` refresh. fp32 (TrainStep's eager-parity dtype); the
    full-model bf16 number is BENCH_MODEL=resnet50_train."""

    def run_bench():
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.jit as jit
        from paddle_tpu.vision.models.resnet import BottleneckBlock

        if os.environ.get("PADDLE_CONV_BACKEND"):
            raise RuntimeError(
                "unset PADDLE_CONV_BACKEND to run the fused-vs-dense "
                "train row")

        xnp = np.random.RandomState(seed) \
            .randn(batch, inplanes, hw, hw).astype(np.float32)
        label = paddle.to_tensor(np.zeros(1, np.float32))

        def build_step(backend):
            paddle.seed(seed)            # identical weights per build
            blk = BottleneckBlock(inplanes, planes,
                                  conv_backend=backend)
            blk.train()
            opt = paddle.optimizer.SGD(
                learning_rate=0.01, parameters=blk.parameters())
            return jit.TrainStep(
                blk, opt, loss_fn=lambda out, lbl: (out * out).mean())

        def timed(step):
            # TrainStep mutates parameters host-side between calls, so
            # it cannot ride the fori_loop _timeit — wall-clock the
            # donated program like bench.py's _run_repeat_steps
            loss = float(step(paddle.to_tensor(xnp.copy()), label))
            t0 = time.perf_counter()
            for _ in range(steps):
                last = step(paddle.to_tensor(xnp.copy()), label)
            float(last)                 # host sync
            return loss, (time.perf_counter() - t0) / steps * 1e3

        loss_d, dense_ms = timed(build_step("dense"))
        loss_p, fused_ms = timed(build_step("pallas"))
        err = abs(loss_p - loss_d) / max(abs(loss_d), 1e-6)
        assert err <= CONV_FUSED_REL_TOL, \
            (f"fused train step diverges from dense (loss rel err "
             f"{err:.4f}, budget {CONV_FUSED_REL_TOL})")
        width = planes
        # 3x the forward conv flops (fwd, dInput, dWeight per conv)
        flops = 3 * 2 * batch * hw * hw * (
            inplanes * width + width * width * 9 + width * inplanes)
        return {"ms": round(fused_ms, 4),
                "dense_ms": round(dense_ms, 4),
                "speedup_vs_dense": round(dense_ms / fused_ms, 3),
                "tflops": round(flops / (fused_ms / 1e3) / 1e12, 2),
                "loss_rel_err": round(err, 6),
                "batch": batch, "hw": hw}

    return run_bench


# Documented tolerance budget for int8 serving (ISSUE 11): the
# quantized engine's greedy token streams must agree with the fp
# engine's on at least this fraction of generated tokens over the
# standard mixed trace (README "Quantized serving" states the policy;
# tests/test_engine_quantized.py enforces it at CI scale).
INT8_TOKEN_PARITY_MIN = 0.90


def _token_match_fraction(ref_outs, got_outs):
    """Fraction of positionally matching tokens across two runs'
    aligned output lists (prompt + generated per request)."""
    match = total = 0
    for a, b in zip(ref_outs, got_outs):
        n = max(len(a), len(b))
        total += n
        match += sum(x == y for x, y in zip(a, b))
    return match / max(total, 1)


def _engine_offered_load_case(model_cfg=None, requests=None, num_slots=8,
                              block_size=16, prefill_buckets=None,
                              seed=0, attention_backend=None,
                              mp_degree=None, kv_dtype=None):
    """Engine-level offered-load row: the continuous-batching engine
    (paged KV cache + slot scheduler, inference/engine.py) serving a
    mixed trace of prompts/output lengths; the metric is AGGREGATE new
    tokens per wall-clock second — the serving-health number the gate
    tracks from this PR on. Self-timed (the scheduler loop is
    host-driven admission between compiled iterations, so _timeit's
    in-graph fori_loop doesn't apply): compile is excluded by warming
    every prefill bucket + the decode step on a throwaway trace first.
    The row also carries the engine's metrics snapshot distilled to
    serving-SLO numbers (TTFT/TPOT percentiles, block stalls, pool
    high-water, recompiles) so BENCH rounds record latency health, not
    just aggregate tokens/s — warmup observations are dropped by a
    registry reset before the measured window.
    Returns a zero-arg runner producing the result record (run()
    resolves it); tests call it with a tiny config.
    `attention_backend` selects the paged-attention kernel
    (`gpt_engine_offered_load_pallas` is this same trace with
    attention_backend='pallas' — the fused-kernel serving number).
    `mp_degree` serves the SAME trace tensor-parallel over an mp-axis
    mesh (`gpt_engine_offered_load_mp2`): the row first serves at mp=1
    for the reference outputs + tokens/s, then at mp_degree, and
    ASSERTS the outputs token-identical — the headline numbers are the
    sharded engine's.
    `kv_dtype='int8'` is the quantized serving row
    (`gpt_engine_offered_load_int8`): the same trace served fp first
    (reference outputs + tokens/s + pool bytes), then with the int8
    per-block-scaled KV cache AND int8 weights; outputs must match
    within the documented tolerance (INT8_TOKEN_PARITY_MIN) and the
    record carries both tokens/s, both pool-byte footprints, and the
    measured match fraction."""

    def run_bench():
        import time

        import numpy as np

        import paddle_tpu  # noqa: F401
        from paddle_tpu.inference import GenerationEngine
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.observability.metrics import (
            quantile_from_buckets, series_total,
        )

        if mp_degree:
            import jax

            if len(jax.devices()) < mp_degree:
                raise RuntimeError(
                    f"bench row needs {mp_degree} devices for mp="
                    f"{mp_degree}, have {len(jax.devices())} — run on "
                    "a TPU slice or a virtual mesh "
                    "(XLA_FLAGS=--xla_force_host_platform_device_count)")
        cfg = model_cfg or GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24,
            num_heads=16, max_seq_len=512)
        rng = np.random.RandomState(seed)
        reqs = requests or [
            (int(rng.randint(24, 193)), int(rng.randint(32, 129)))
            for _ in range(24)]                # (prompt_len, max_new)
        prompts = [rng.randint(0, cfg.vocab_size, plen)
                   for plen, _ in reqs]
        model = GPTForCausalLM(cfg)
        model.eval()
        buckets = prefill_buckets or tuple(
            b for b in (32, 64, 128, 256, cfg.max_seq_len)
            if b <= cfg.max_seq_len)

        def build(mp, quant=False):
            qkw = dict(kv_dtype="int8", weight_dtype="int8") \
                if quant else {}
            engine = GenerationEngine(model, num_slots=num_slots,
                                      block_size=block_size,
                                      prefill_buckets=buckets,
                                      attention_backend=attention_backend,
                                      mp_degree=mp, **qkw)
            if not quant and (engine.kv_dtype is not None
                              or engine.weight_dtype is not None):
                # either env knob would silently quantize the fp
                # reference too, making the parity numbers a lie
                raise RuntimeError(
                    "the fp reference engine resolved kv_dtype="
                    f"{engine.kv_dtype!r} / weight_dtype="
                    f"{engine.weight_dtype!r} (is PADDLE_SERVE_KV_DTYPE"
                    " or PADDLE_SERVE_WEIGHT_DTYPE set?) — unset them "
                    "to run this row")
            if mp and engine.mp_degree != mp:
                # a row NAMED for an mp degree must never record an
                # env-overridden mesh's numbers under that name
                raise RuntimeError(
                    f"bench row requested mp_degree={mp} but the "
                    f"engine resolved {engine.mp_degree} (is "
                    "PADDLE_SERVE_MP set?) — unset it to run this row")
            if attention_backend and \
                    engine.attention_backend != attention_backend:
                # the env knob overrides the constructor (deploy
                # semantics) — but a bench row NAMED for a backend must
                # never record another backend's numbers under that name
                raise RuntimeError(
                    f"bench row requested attention_backend="
                    f"{attention_backend!r} but the engine resolved "
                    f"{engine.attention_backend!r} (is "
                    "PADDLE_PAGED_ATTENTION_BACKEND set?) — unset it "
                    "to run this row")
            return engine

        def serve(engine, warm_rng_seed=1):
            """Warm every compiled program the trace will hit (bucketed
            prefill per bucket + the one decode step), then measure."""
            wrng = np.random.RandomState(warm_rng_seed)
            for b in sorted({engine._bucket_for(p) for p, _ in reqs}):
                warm_len = min(b, engine.max_model_len - 2)
                engine.add_request(
                    wrng.randint(0, cfg.vocab_size, warm_len),
                    max_new_tokens=2)
            engine.run()
            base = engine.tokens_generated
            engine.metrics.reset()         # drop warmup observations
            ids = [engine.add_request(p, max_new_tokens=max_new)
                   for p, (_, max_new) in zip(prompts, reqs)]
            t0 = time.perf_counter()
            out = engine.run()
            dt = time.perf_counter() - t0
            new_toks = engine.tokens_generated - base
            assert len(out) == len(reqs)
            return dt, new_toks, [list(map(int, out[i])) for i in ids]

        mp_extra = {}
        if kv_dtype:
            if kv_dtype != "int8":
                raise ValueError(
                    f"kv_dtype={kv_dtype!r}: only 'int8' is benched")
            ref_engine = build(None)
            dt1, toks1, outs1 = serve(ref_engine)
            fp_bytes = ref_engine.cache.pool_nbytes()
            engine = build(None, quant=True)
            dt, new_toks, outs = serve(engine)
            match = _token_match_fraction(outs1, outs)
            assert match >= INT8_TOKEN_PARITY_MIN, \
                (f"int8 outputs match only {match:.3f} of fp tokens "
                 f"(tolerance budget {INT8_TOKEN_PARITY_MIN})")
            q_bytes = engine.cache.pool_nbytes()
            mp_extra = {"kv_dtype": "int8", "weight_dtype": "int8",
                        "tokens_per_s_fp": round(toks1 / dt1),
                        "token_match_fraction": round(match, 4),
                        "pool_bytes_fp": fp_bytes,
                        "pool_bytes_int8": q_bytes,
                        "pool_bytes_ratio": round(q_bytes / fp_bytes,
                                                  4)}
        elif mp_degree:
            if mp_degree < 2:
                raise ValueError(
                    f"mp_degree={mp_degree}: the sharded row compares "
                    "against mp=1 — ask for a degree >= 2")
            # reference serve at mp=1: the parity oracle AND the
            # single-chip tokens/s this row's speedup is judged against
            ref_engine = build(None)
            if ref_engine.mp_degree != 1:
                # PADDLE_SERVE_MP would silently shard the "mp=1"
                # baseline too, making the parity assert vacuous and
                # tokens_per_s_mp1 a lie
                raise RuntimeError(
                    "the mp=1 reference engine resolved mp="
                    f"{ref_engine.mp_degree} (is PADDLE_SERVE_MP "
                    "set?) — unset it to run this row")
            dt1, toks1, outs1 = serve(ref_engine)
            engine = build(mp_degree)
            dt, new_toks, outs = serve(engine)
            assert outs == outs1, \
                f"mp={mp_degree} outputs diverged from mp=1"
            mp_extra = {"mp_degree": mp_degree,
                        "devices": engine.mesh.size,
                        "tokens_per_s_mp1": round(toks1 / dt1)}
        else:
            engine = build(None)
            dt, new_toks, _ = serve(engine)

        snap = engine.metrics_snapshot()

        def pct_ms(name, q):
            fam = snap[name]
            if not fam["series"]:
                return None
            v = quantile_from_buckets(fam["buckets"],
                                      fam["series"][0]["counts"], q)
            return None if v is None else round(v * 1e3, 3)

        return {"ms": round(dt * 1e3, 1),
                "tokens_per_s": round(new_toks / dt),
                "attention_backend": engine.attention_backend,
                "requests": len(reqs),
                "ttft_ms_p50": pct_ms("engine_ttft_seconds", 0.5),
                "ttft_ms_p99": pct_ms("engine_ttft_seconds", 0.99),
                "tpot_ms_p50": pct_ms("engine_tpot_seconds", 0.5),
                "tpot_ms_p99": pct_ms("engine_tpot_seconds", 0.99),
                "block_stalls": int(series_total(
                    snap, "engine_block_stalls_total")),
                "pool_high_water_blocks": int(
                    snap["engine_pool_used_high_water_blocks"]
                    ["series"][0]["value"]),
                "decode_recompiles": int(series_total(
                    snap, "engine_decode_recompiles_total")),
                **mp_extra}

    return run_bench


def _tpot_pct(snap, q):
    """Tail TPOT from the engine's histogram, counts summed across the
    priority-labeled series (ms, or None before any observation)."""
    return _hist_pct(snap, "engine_tpot_seconds", q)


def _hist_pct(snap, name, q):
    """Quantile of any snapshot histogram with counts summed across
    ALL its labeled series (priority/replica/...): the fleet-level
    percentile view (ms, or None before any observation)."""
    from paddle_tpu.observability.metrics import quantile_from_buckets

    fam = snap[name]
    if not fam["series"]:
        return None
    counts = [sum(s["counts"][i] for s in fam["series"])
              for i in range(len(fam["series"][0]["counts"]))]
    v = quantile_from_buckets(fam["buckets"], counts, q)
    return None if v is None else round(v * 1e3, 3)


def _fleet_offered_load_case(model_cfg=None, num_tenants=3,
                             per_tenant=8, uniques=6, prefix_len=64,
                             suffix_max=32, max_new=32, num_slots=8,
                             block_size=16, prefill_chunk=64, seed=0,
                             replica_counts=(1, 2)):
    """Serving-tier offered-load row (ISSUE 12): the SAME skewed
    multi-tenant trace served by a 1-replica and an N-replica
    `ServingFleet` (prefix-affinity dp router over engine replicas,
    inference/fleet.py). The trace is deliberately skewed — tenant 0's
    hot shared system prompt carries ~half the requests, later tenants
    halve, plus a long-tail of one-off prompts — the shape where
    affinity routing either pays (hot prefixes stay on the replica
    owning their warm blocks) or collapses a replica (no hysteresis).
    Each fleet serves the trace twice: the COLD wave is the tracked
    offered-load number per replica count, the WARM wave (fresh
    suffixes, same tenants) must route hot tenants onto their warm
    blocks — the runner ASSERTS merged prefix-cache hit tokens AND
    router affinity tokens > 0, and asserts every request's output
    token-identical across replica counts (the fleet exactness
    contract at bench scale). Tracked numbers: aggregate cold
    tokens/s at each replica count, warm tokens/s, p99 TTFT/TPOT from
    the replica-labeled merged snapshot."""

    def run_bench():
        import time

        import numpy as np

        import paddle_tpu  # noqa: F401
        from paddle_tpu.inference import ServingFleet
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.observability.metrics import series_total

        cfg = model_cfg or GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24,
            num_heads=16, max_seq_len=512)
        rng = np.random.RandomState(seed)
        model = GPTForCausalLM(cfg)
        model.eval()
        tenants = [rng.randint(0, cfg.vocab_size, prefix_len)
                   for _ in range(num_tenants)]

        def wave():
            # skewed: tenant t carries per_tenant >> t requests
            reqs = []
            lo = max(1, min(8, max_new))
            for t, pre in enumerate(tenants):
                for _ in range(max(1, per_tenant >> t)):
                    sfx = rng.randint(0, cfg.vocab_size,
                                      rng.randint(1, suffix_max + 1))
                    reqs.append((np.concatenate([pre, sfx]),
                                 int(rng.randint(lo, max_new + 1))))
            for _ in range(uniques):
                reqs.append((rng.randint(
                    0, cfg.vocab_size,
                    rng.randint(prefix_len // 2, prefix_len * 2)),
                    int(rng.randint(lo, max_new + 1))))
            return reqs

        # both waves fixed up front so every fleet serves the same
        # bytes — the cross-replica-count identity assert needs it
        trace_cold, trace_warm = wave(), wave()

        def fleet_tokens(fleet):
            return sum(r.engine.tokens_generated
                       for r in fleet._replicas.values())

        def serve(fleet, trace):
            base = fleet_tokens(fleet)
            t0 = time.perf_counter()
            ids = [fleet.add_request(p, max_new_tokens=n)
                   for p, n in trace]
            out = fleet.run()
            dt = time.perf_counter() - t0
            assert len(out) == len(trace)
            return dt, fleet_tokens(fleet) - base, \
                [list(map(int, out[i])) for i in ids]

        results, outs_by_n = {}, {}
        for n in replica_counts:
            fleet = ServingFleet(model, num_replicas=n,
                                 num_slots=num_slots,
                                 block_size=block_size,
                                 prefill_chunk=prefill_chunk)
            eng0 = fleet._any_engine()
            if eng0.kv_dtype is not None or eng0.mp_degree != 1:
                # an env knob would silently change every replica,
                # making the replica-count comparison a lie
                raise RuntimeError(
                    "fleet bench replicas resolved kv_dtype="
                    f"{eng0.kv_dtype!r}/mp={eng0.mp_degree} (is a "
                    "PADDLE_SERVE_* env set?) — unset it to run this "
                    "row")
            # compile warmup per replica, off the record
            for rep in fleet._replicas.values():
                rep.engine.add_request(
                    rng.randint(0, cfg.vocab_size, prefill_chunk + 1),
                    max_new_tokens=2)
                rep.engine.run()
            fleet.reset_metrics()
            dt_cold, toks_cold, outs_cold = serve(fleet, trace_cold)
            snap = fleet.metrics_snapshot()
            ttft99 = _hist_pct(snap, "engine_ttft_seconds", 0.99)
            tpot99 = _hist_pct(snap, "engine_tpot_seconds", 0.99)
            fleet.reset_metrics()
            dt_warm, toks_warm, outs_warm = serve(fleet, trace_warm)
            snap = fleet.metrics_snapshot()
            hit = int(series_total(
                snap, "engine_prefix_cache_hit_tokens_total"))
            aff = int(series_total(
                snap, "fleet_affinity_hit_tokens_total"))
            assert hit > 0, \
                "warm wave must serve prefix-cache hits fleet-wide"
            assert aff > 0, \
                ("warm wave must land affinity routes (hot tenants "
                 "onto their block-owning replica)")
            outs_by_n[n] = outs_cold + outs_warm
            results[n] = {
                "tokens_per_s": round(toks_cold / dt_cold),
                "tokens_per_s_warm": round(toks_warm / dt_warm),
                "ms": round(dt_cold * 1e3, 1),
                "ttft_ms_p99": ttft99, "tpot_ms_p99": tpot99,
                "affinity_hit_tokens": aff,
                "prefix_hit_tokens": hit}
        base_n = replica_counts[0]
        for n in replica_counts[1:]:
            assert outs_by_n[n] == outs_by_n[base_n], \
                (f"fleet outputs diverged between replicas={base_n} "
                 f"and replicas={n}")
        head = results[replica_counts[-1]]
        return {**head,
                "replicas": replica_counts[-1],
                "requests": len(trace_cold) + len(trace_warm),
                **{f"tokens_per_s_r{n}": results[n]["tokens_per_s"]
                   for n in replica_counts}}

    return run_bench


def _engine_multitenant_lora_case(model_cfg=None, num_tenants=4,
                                  per_tenant=6, rank=8, max_rank=8,
                                  prefix_len=48, suffix_max=24,
                                  max_new=24, num_slots=8,
                                  block_size=16, prefill_chunk=64,
                                  adapter_pool_pages=None, seed=0):
    """Multi-tenant batched-LoRA serving row (ISSUE 13): one base
    model, `num_tenants` per-tenant adapters, a SKEWED trace (tenant t
    carries `per_tenant >> t` requests, each a tenant system prompt +
    fresh suffix) served two ways:

    - MIXED (the subsystem under test): ONE engine with the full
      adapter registry serves every tenant's requests interleaved —
      the paged adapter pool gathers per-slot pages inside the one
      compiled decode step, so the batch stays full across tenants.
    - STRAWMAN: one dedicated engine per tenant (the pre-LoRA shape:
      fork the engine per adapter), each serving only its own
      requests, timed end to end sequentially — lanes idle whenever a
      tenant has fewer live requests than slots.

    The runner ASSERTS every request's output token-identical between
    the two (the mixed-tenant exactness contract at bench scale) and
    decode_traces == 1 on the mixed engine regardless of how many
    adapters are live. Tracked numbers: mixed vs dedicated aggregate
    tokens/s (+ the speedup), adapter-pool swap-ins/evictions, and
    per-tenant p99 TTFT/TPOT off the adapter-labeled histograms —
    the per-tenant SLO view only the mixed engine can even report."""

    def run_bench():
        import time

        import numpy as np

        import paddle_tpu  # noqa: F401
        from paddle_tpu.adapters import AdapterRegistry
        from paddle_tpu.inference import GenerationEngine
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.observability.metrics import (
            quantile_from_buckets, series_total,
        )

        cfg = model_cfg or GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24,
            num_heads=16, max_seq_len=512)
        rng = np.random.RandomState(seed)
        model = GPTForCausalLM(cfg)
        model.eval()
        reg = AdapterRegistry(cfg, max_rank=max_rank)
        H, I, L = (cfg.hidden_size, cfg.intermediate_size,
                   cfg.num_layers)
        for t in range(num_tenants):
            w = {}
            for site, (i_d, o_d) in (("qkv", (H, 3 * H)),
                                     ("out", (H, H)), ("fc1", (H, I)),
                                     ("fc2", (I, H))):
                w[site] = [
                    (rng.randn(rank, i_d).astype(np.float32) * 0.05,
                     rng.randn(o_d, rank).astype(np.float32) * 0.05)
                    for _ in range(L)]
            reg.register(t + 1, w, alpha=2 * rank)
        tenants = [rng.randint(0, cfg.vocab_size, prefix_len)
                   for _ in range(num_tenants)]
        # skewed trace: tenant t carries per_tenant >> t requests
        reqs = []
        for t, pre in enumerate(tenants):
            for _ in range(max(1, per_tenant >> t)):
                sfx = rng.randint(0, cfg.vocab_size,
                                  rng.randint(1, suffix_max + 1))
                reqs.append((np.concatenate([pre, sfx]), t + 1,
                             int(rng.randint(max(2, max_new // 2),
                                             max_new + 1))))
        order = rng.permutation(len(reqs))
        # stable per-request ids so the mixed run and the per-tenant
        # dedicated runs key the same request identically
        reqs = [(f"r{i}", *reqs[j]) for i, j in enumerate(order)]

        def build(adapters):
            eng = GenerationEngine(
                model, num_slots=num_slots, block_size=block_size,
                prefill_chunk=prefill_chunk, adapters=adapters,
                adapter_pool_pages=adapter_pool_pages
                if adapters is not None else None)
            if eng.kv_dtype is not None or eng.mp_degree != 1:
                raise RuntimeError(
                    "lora bench engine resolved kv_dtype="
                    f"{eng.kv_dtype!r}/mp={eng.mp_degree} (is a "
                    "PADDLE_SERVE_* env set?) — unset it to run this "
                    "row")
            # compile warmup off the record (chunk + decode programs)
            eng.add_request(
                rng.randint(0, cfg.vocab_size, prefill_chunk + 1),
                max_new_tokens=2)
            eng.run()
            eng.metrics.reset()
            return eng

        def serve(eng, batch):
            base = eng.tokens_generated
            t0 = time.perf_counter()
            ids = [eng.add_request(p, max_new_tokens=n, adapter_id=a,
                                   req_id=rid)
                   for rid, p, a, n in batch]
            out = eng.run()
            dt = time.perf_counter() - t0
            return dt, eng.tokens_generated - base, \
                {i: list(map(int, out[i])) for i in ids}, ids

        mixed = build(reg)
        dt_mix, toks_mix, out_mix, _ = serve(mixed, reqs)
        assert mixed.decode_traces == 1, \
            "mixed-tenant decode retraced — the adapter row must be " \
            "traced, never a trace key"
        snap = mixed.metrics_snapshot()
        swapins = int(series_total(snap,
                                   "engine_adapter_swapins_total"))
        evictions = int(series_total(
            snap, "engine_adapter_evictions_total"))

        def tenant_pct(name, q):
            fam = snap[name]
            out = {}
            for s in fam["series"]:
                v = quantile_from_buckets(fam["buckets"], s["counts"],
                                          q)
                if v is not None:
                    out[s["labels"]["adapter"]] = round(v * 1e3, 3)
            return out

        # strawman: per-tenant dedicated engines, timed sequentially
        dt_ded, toks_ded, out_ded = 0.0, 0, {}
        for t in range(num_tenants):
            mine = [r for r in reqs if r[2] == t + 1]
            if not mine:
                continue
            ded = build(reg)
            dt, toks, outs, _ = serve(ded, mine)
            dt_ded += dt
            toks_ded += toks
            out_ded.update(outs)
        assert len(out_ded) == len(out_mix)
        match = _token_match_fraction(
            [out_mix[i] for i in sorted(out_mix, key=str)],
            [out_ded[i] for i in sorted(out_ded, key=str)])
        assert match == 1.0, \
            (f"mixed-tenant outputs diverged from dedicated engines "
             f"(match {match:.4f}) — cross-slot adapter leakage")
        return {"tokens_per_s": round(toks_mix / dt_mix),
                "tokens_per_s_dedicated": round(toks_ded / dt_ded),
                "speedup_vs_dedicated": round(
                    (toks_mix / dt_mix) / (toks_ded / dt_ded), 3),
                "ms": round(dt_mix * 1e3, 1),
                "tenants": num_tenants, "requests": len(reqs),
                "rank": rank, "max_rank": max_rank,
                "adapter_swapins": swapins,
                "adapter_evictions": evictions,
                "ttft_ms_p99_by_tenant": tenant_pct(
                    "engine_adapter_ttft_seconds", 0.99),
                "tpot_ms_p99_by_tenant": tenant_pct(
                    "engine_adapter_tpot_seconds", 0.99),
                "decode_recompiles": int(series_total(
                    snap, "engine_decode_recompiles_total"))}

    return run_bench


def _engine_prefix_cache_case(model_cfg=None, num_tenants=4,
                              per_tenant=6, uniques=8, prefix_len=64,
                              suffix_max=32, max_new=32, num_slots=8,
                              block_size=16, prefill_chunk=64, seed=0):
    """Prefix-cache serving row: a multi-tenant trace (each tenant is a
    hot shared system prompt carried by `per_tenant` requests with
    unique suffixes, plus `uniques` long-tail one-off prompts) served
    twice by ONE engine. The first wave computes and publishes every
    tenant prefix; the second wave (fresh suffixes, same tenants) must
    seat the shared blocks from the cache — the record proves it with
    the hit-token counter and a strictly lower prefill-chunk count,
    and the tracked numbers are warm tokens/s + warm tail TPOT vs the
    cold wave's. Runs chunked prefill + prefix cache (the default
    scheduler this row exists to track)."""

    def run_bench():
        import time

        import numpy as np

        import paddle_tpu  # noqa: F401
        from paddle_tpu.inference import GenerationEngine
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.observability.metrics import series_total

        cfg = model_cfg or GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24,
            num_heads=16, max_seq_len=512)
        rng = np.random.RandomState(seed)
        model = GPTForCausalLM(cfg)
        model.eval()
        engine = GenerationEngine(model, num_slots=num_slots,
                                  block_size=block_size,
                                  prefill_chunk=prefill_chunk)
        tenants = [rng.randint(0, cfg.vocab_size, prefix_len)
                   for _ in range(num_tenants)]

        def wave():
            reqs = []
            for pre in tenants:
                for _ in range(per_tenant):
                    sfx = rng.randint(0, cfg.vocab_size,
                                      rng.randint(1, suffix_max + 1))
                    reqs.append(np.concatenate([pre, sfx]))
            for _ in range(uniques):
                reqs.append(rng.randint(
                    0, cfg.vocab_size,
                    rng.randint(prefix_len // 2, prefix_len * 2)))
            return reqs

        def serve(reqs):
            base = engine.tokens_generated
            t0 = time.perf_counter()
            for p in reqs:
                engine.add_request(p, max_new_tokens=max_new)
            out = engine.run()
            dt = time.perf_counter() - t0
            assert len(out) == len(reqs)
            return dt, engine.tokens_generated - base

        # compile warmup (chunk + decode programs), off the record
        engine.add_request(
            rng.randint(0, cfg.vocab_size, prefill_chunk + 1), 2)
        engine.run()
        engine.metrics.reset()
        dt_cold, toks_cold = serve(wave())
        snap = engine.metrics_snapshot()
        chunks_cold = series_total(snap, "engine_prefill_chunks_total")
        tpot_cold = _tpot_pct(snap, 0.99)
        engine.metrics.reset()
        dt_warm, toks_warm = serve(wave())   # fresh suffixes, hot cache
        snap = engine.metrics_snapshot()
        chunks_warm = series_total(snap, "engine_prefill_chunks_total")
        hit = int(series_total(snap,
                               "engine_prefix_cache_hit_tokens_total"))
        assert hit > 0, "warm wave must serve prefix hits"
        assert chunks_warm < chunks_cold, \
            "prefix hits must shrink prefill compute"
        return {"ms": round(dt_warm * 1e3, 1),
                "tokens_per_s": round(toks_warm / dt_warm),
                "cold_tokens_per_s": round(toks_cold / dt_cold),
                "hit_tokens": hit,
                "prefill_chunks_cold": int(chunks_cold),
                "prefill_chunks_warm": int(chunks_warm),
                "tpot_ms_p99": _tpot_pct(snap, 0.99),
                "tpot_ms_p99_cold": tpot_cold,
                "cached_blocks": int(
                    snap["engine_prefix_cached_blocks"]["series"][0]
                    ["value"]),
                "requests_per_wave":
                    num_tenants * per_tenant + uniques}

    return run_bench


def _engine_chunked_prefill_case(model_cfg=None, long_prompt=384,
                                 decode_lanes=4, max_new=48,
                                 num_slots=6, block_size=16,
                                 prefill_chunk=64, seed=0):
    """Chunked-prefill tail-latency row: `decode_lanes` short-prompt
    requests decode steadily while a LONG prompt is admitted mid-
    stream — once through the chunked scheduler (one chunk per
    iteration interleaves with decode) and once through the legacy
    whole-prompt bucketed prefill (the admission monopolizes an
    iteration). The tracked numbers are the decode lanes' tail TPOT
    under each mode; on TPU the whole-prompt p99 spikes by the full
    long-prefill latency while the chunked p99 is bounded by one
    chunk. (CPU CI only asserts both modes run and report.)"""

    def run_bench():
        import time

        import numpy as np

        import paddle_tpu  # noqa: F401
        from paddle_tpu.inference import GenerationEngine
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = model_cfg or GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24,
            num_heads=16, max_seq_len=512)
        rng = np.random.RandomState(seed)
        model = GPTForCausalLM(cfg)
        model.eval()
        short = [rng.randint(0, cfg.vocab_size,
                             rng.randint(4, 2 * block_size))
                 for _ in range(decode_lanes)]
        long_p = rng.randint(0, cfg.vocab_size, long_prompt)

        def serve(**engine_kw):
            engine = GenerationEngine(model, num_slots=num_slots,
                                      block_size=block_size,
                                      **engine_kw)
            # warm every compiled program off the record (the chunked
            # engine runs cache-off so this warm-up cannot seed prefix
            # hits that would skip the prefill being measured)
            engine.add_request(long_p, 2)
            engine.add_request(short[0], 2)
            engine.run()
            engine.metrics.reset()
            t0 = time.perf_counter()
            for p in short:
                engine.add_request(p, max_new_tokens=max_new)
            for _ in range(3):
                engine.step()          # lanes are decoding...
            engine.add_request(long_p, max_new_tokens=8)  # ...bomb
            out = engine.run()
            dt = time.perf_counter() - t0
            assert len(out) == decode_lanes + 1
            return dt, _tpot_pct(engine.metrics_snapshot(), 0.99)

        dt_chunked, p99_chunked = serve(prefill_chunk=prefill_chunk,
                                        enable_prefix_cache=False)
        buckets = tuple(b for b in (32, 64, 128, 256, cfg.max_seq_len)
                        if b <= cfg.max_seq_len)
        _, p99_whole = serve(prefill_buckets=buckets)
        return {"ms": round(dt_chunked * 1e3, 1),
                "prefill_chunk": prefill_chunk,
                "long_prompt": long_prompt,
                "tpot_ms_p99_chunked": p99_chunked,
                "tpot_ms_p99_whole": p99_whole}

    return run_bench


def _engine_speculative_case(model_cfg=None, num_requests=12,
                             num_slots=4, block_size=16,
                             prefill_chunk=64, spec_k=4, max_new=48,
                             seed=0):
    """Speculative-decoding offered-load row (ISSUE 7): one trace of
    REPETITIVE prompts (tiled motifs — the prompt-lookup drafter's
    favorable case, standing in for summarization/code workloads that
    repeat prompt spans) served by two engines over the same model:
    the K=0 baseline and the speculative engine at `spec_k`. The
    tracked numbers are net tokens/s under speculation vs the K=0
    baseline, accepted tokens per verify step, and the draft hit rate
    — the amortization evidence the tentpole claims. The two runs'
    outputs are asserted token-identical (the exact-acceptance
    contract, re-proven at bench scale). On TPU the speedup is the
    headline; CPU CI only asserts structure."""

    def run_bench():
        import time

        import numpy as np

        import paddle_tpu  # noqa: F401
        from paddle_tpu.inference import GenerationEngine
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.observability.metrics import series_total

        cfg = model_cfg or GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24,
            num_heads=16, max_seq_len=512)
        rng = np.random.RandomState(seed)
        reqs = []
        for _ in range(num_requests):
            motif = rng.randint(0, cfg.vocab_size, rng.randint(4, 9))
            p = np.tile(motif, 12)[:cfg.max_seq_len - max_new - 1]
            reqs.append(p.astype(np.int32))
        model = GPTForCausalLM(cfg)
        model.eval()

        def serve(k):
            engine = GenerationEngine(model, num_slots=num_slots,
                                      block_size=block_size,
                                      prefill_chunk=prefill_chunk,
                                      spec_decode_k=k)
            if engine.spec_decode_k != k:
                # a row comparing K=spec_k against K=0 must never
                # record an env-overridden K under either name
                raise RuntimeError(
                    f"bench row requested spec_decode_k={k} but the "
                    f"engine resolved {engine.spec_decode_k} (is "
                    "PADDLE_SPEC_DECODE_K set?) — unset it to run "
                    "this row")
            engine.add_request(reqs[0], 2)     # compile warmup
            engine.run()
            engine.metrics.reset()
            base = engine.tokens_generated
            t0 = time.perf_counter()
            ids = [engine.add_request(p, max_new_tokens=max_new)
                   for p in reqs]
            out = engine.run()
            dt = time.perf_counter() - t0
            toks = engine.tokens_generated - base
            assert len(out) == num_requests
            return engine, dt, toks, [out[r] for r in ids]

        eng0, dt0, toks0, outs0 = serve(0)
        engk, dtk, toksk, outsk = serve(spec_k)
        for a, b in zip(outs0, outsk):         # exact acceptance
            assert a == b, "speculative output diverged from K=0"
        snap = engk.metrics_snapshot()
        fam = snap["engine_spec_accepted_tokens"]["series"][0]
        steps = max(int(fam["count"]), 1)
        return {"ms": round(dtk * 1e3, 1),
                "tokens_per_s": round(toksk / dtk),
                "tokens_per_s_k0": round(toks0 / dt0),
                "speedup_vs_k0": round((toksk / dtk) / (toks0 / dt0),
                                       3),
                "spec_k": spec_k,
                "accepted_tokens_per_step": round(fam["sum"] / steps,
                                                  3),
                "draft_hit_rate": round(
                    snap["engine_spec_draft_hit_rate"]["series"][0]
                    ["value"], 4),
                "verify_steps": int(fam["count"]),
                "decode_recompiles": int(series_total(
                    snap, "engine_decode_recompiles_total")),
                "requests": num_requests}

    return run_bench


def _engine_sampling_case(model_cfg=None, num_requests=12,
                          num_slots=4, block_size=16, max_new=32,
                          best_n=4, seed=0):
    """Probabilistic-serving row (ISSUE 15): the offered-load trace
    served three ways on one sampling-enabled engine over one model —
    greedy (temperature 0, asserted TOKEN-IDENTICAL to a sampling-OFF
    engine: the bit-exact no-regression contract at bench scale),
    temperature 0.8 sampled (same fixed seeds served twice, asserted
    reproducible token-for-token), and a best-of-`best_n` fan-out of
    one prompt (asserted to seat the shared prompt blocks ONCE via the
    prefix-hit counter). The tracked numbers are tokens/s for all
    three modes — the cost of the on-device masking+draw relative to
    the pure-argmax step — plus the sampled-token and prefix-hit
    counters. On TPU the overhead is the headline; CPU CI only asserts
    structure."""

    def run_bench():
        import time

        import numpy as np

        import paddle_tpu  # noqa: F401
        from paddle_tpu.inference import (GenerationEngine,
                                          SamplingParams)
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.observability.metrics import series_total

        cfg = model_cfg or GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24,
            num_heads=16, max_seq_len=512)
        rng = np.random.RandomState(seed)
        # prompt + budget must fit the model window (tiny CI configs)
        hi = min(97, cfg.max_seq_len - max_new)
        lo = min(16, hi - 1)
        reqs = [rng.randint(0, cfg.vocab_size,
                            rng.randint(lo, hi)).astype(np.int32)
                for _ in range(num_requests)]
        model = GPTForCausalLM(cfg)
        model.eval()

        def build(on):
            engine = GenerationEngine(model, num_slots=num_slots,
                                      block_size=block_size,
                                      sampling=on)
            if engine.sampling != on:
                # a row comparing sampling-on against sampling-off
                # must never record an env-overridden engine's
                # numbers under either name
                raise RuntimeError(
                    f"bench row requested sampling={on} but the "
                    f"engine resolved {engine.sampling} (is "
                    "PADDLE_SERVE_SAMPLING set?) — unset it to run "
                    "this row")
            return engine

        def serve(engine, params_of):
            engine.add_request(reqs[0], 2)     # compile warmup
            engine.run()
            engine.metrics.reset()
            base = engine.tokens_generated
            t0 = time.perf_counter()
            ids = [engine.add_request(p, max_new_tokens=max_new,
                                      sampling_params=params_of(i))
                   for i, p in enumerate(reqs)]
            out = engine.run()
            dt = time.perf_counter() - t0
            toks = engine.tokens_generated - base
            assert len(out) == num_requests
            return dt, toks, [out[r] for r in ids]

        ref = build(False)
        dt_ref, toks_ref, outs_ref = serve(ref, lambda i: None)
        eng = build(True)
        dt_g, toks_g, outs_g = serve(eng, lambda i: None)
        assert outs_g == outs_ref, \
            "temperature-0 serving diverged from the sampling-off " \
            "engine (the bit-exact greedy contract)"
        sp = lambda i: SamplingParams(temperature=0.8, top_k=50,
                                      top_p=0.95, seed=seed + i)
        eng_s = build(True)
        dt_s, toks_s, outs_s = serve(eng_s, sp)
        _, _, outs_s2 = serve(build(True), sp)
        assert outs_s == outs_s2, \
            "same-seed sampled serving is not reproducible"
        snap = eng_s.metrics_snapshot()
        sampled = int(series_total(snap,
                                   "engine_sampled_tokens_total"))
        bo = build(True)
        hit0 = bo.prefix_hit_tokens
        t0 = time.perf_counter()
        cands = bo.best_of_n(reqs[0], best_n, max_new,
                             sampling_params=SamplingParams(
                                 temperature=0.8, seed=seed))
        dt_b = time.perf_counter() - t0
        shared = (len(reqs[0]) // block_size) * block_size
        assert bo.prefix_hit_tokens - hit0 == (best_n - 1) * shared, \
            "best_of_n did not seat the shared prompt blocks once"
        toks_b = sum(len(c) - len(reqs[0]) for c in cands)
        return {"ms": round(dt_s * 1e3, 1),
                "tokens_per_s_greedy_off": round(toks_ref / dt_ref),
                "tokens_per_s_greedy": round(toks_g / dt_g),
                "tokens_per_s_sampled": round(toks_s / dt_s),
                "sampling_overhead_vs_off": round(
                    (toks_ref / dt_ref) / max(toks_s / dt_s, 1e-9),
                    3),
                "tokens_per_s_best_of_n": round(toks_b / dt_b),
                "best_n": best_n,
                "sampled_tokens": sampled,
                "best_of_n_hit_tokens": int(
                    bo.prefix_hit_tokens - hit0),
                "requests": num_requests}

    return run_bench


def _engine_host_gap_case(model_cfg=None, num_requests=12,
                          num_slots=4, block_size=16, max_new=32,
                          seed=0):
    """Host-gap row (ISSUE 17 — ROADMAP item 3's measured baseline):
    the offered-load trace served on a tracing-enabled engine at
    K in {0, 4}, cold (first serve after construction — compiles land
    in the dispatch phase) and warm (metrics reset, second serve).
    The tracked numbers are host-gap milliseconds per step BY PHASE
    (schedule/prefix_lookup/dispatch/device_wait/draft_propose/
    accept_walk/cow/finish — the `engine_step_host_gap_seconds`
    histogram, sum/count per phase) plus the device fraction
    (device_wait over the phase total), i.e. how much of every
    scheduler iteration is serial host work the async core of ROADMAP
    item 3 could overlap. On CPU the fraction is meaningless as an
    absolute; the row exists so a TPU `--save` pins the baseline the
    overlap claim is measured against."""

    def run_bench():
        import time

        import numpy as np

        import paddle_tpu  # noqa: F401
        from paddle_tpu.inference import GenerationEngine
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = model_cfg or GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24,
            num_heads=16, max_seq_len=512)
        rng = np.random.RandomState(seed)
        # prompt + budget must fit the model window (tiny CI configs)
        hi = min(97, cfg.max_seq_len - max_new)
        lo = min(16, hi - 1)
        reqs = [rng.randint(0, cfg.vocab_size,
                            rng.randint(lo, hi)).astype(np.int32)
                for _ in range(num_requests)]
        model = GPTForCausalLM(cfg)
        model.eval()

        def build(k):
            engine = GenerationEngine(model, num_slots=num_slots,
                                      block_size=block_size,
                                      spec_decode_k=k, tracing=True)
            if not engine.tracing:
                # a host-gap row without its spans/phases is a
                # different measurement — never record it as this one
                raise RuntimeError(
                    "bench row requested tracing=True but the engine "
                    "resolved tracing off (is PADDLE_SERVE_TRACING "
                    "set?) — unset it to run this row")
            return engine

        def serve(engine):
            base = engine.tokens_generated
            t0 = time.perf_counter()
            for p in reqs:
                engine.add_request(p, max_new_tokens=max_new)
            out = engine.run()
            dt = time.perf_counter() - t0
            assert len(out) == num_requests
            return dt, engine.tokens_generated - base

        def phase_report(engine):
            """(phase -> ms/step, device fraction) from the host-gap
            histogram accumulated since the last metrics reset."""
            snap = engine.metrics_snapshot()
            series = snap["engine_step_host_gap_seconds"]["series"]
            per_step, sums = {}, {}
            for s in series:
                if not s["count"]:
                    continue
                ph = s["labels"]["phase"]
                sums[ph] = s["sum"]
                per_step[ph] = round(s["sum"] / s["count"] * 1e3, 4)
            total = sum(sums.values())
            frac = round(sums.get("device_wait", 0.0) / total, 4) \
                if total else 0.0
            return per_step, frac

        rec = {}
        for k in (0, 4):
            eng = build(k)
            dt_cold, toks_cold = serve(eng)       # includes compiles
            cold, frac_cold = phase_report(eng)
            eng.metrics.reset()
            dt_warm, toks_warm = serve(eng)
            warm, frac_warm = phase_report(eng)
            rec[f"k{k}"] = {
                "phase_ms_per_step_cold": cold,
                "phase_ms_per_step_warm": warm,
                "device_fraction_cold": frac_cold,
                "device_fraction_warm": frac_warm,
                "tokens_per_s_warm": round(toks_warm / dt_warm),
                "spans": int(eng.tracer.total_recorded),
            }
            if k == 0:
                ms_warm = dt_warm * 1e3
        return {"ms": round(ms_warm, 1), **rec,
                "requests": num_requests}

    return run_bench


def _engine_async_overlap_case(model_cfg=None, num_requests=24,
                               num_slots=2, block_size=16, max_new=6,
                               spec_k=4, reps=3, seed=0):
    """Async-core overlap row (ISSUE 18 — the refactor the host-gap
    row was built to measure): the SAME offered-load trace served by a
    persistent serial engine (`async_core=False`) and a persistent
    async engine (`async_core=True`), interleaved serial/async for
    `reps` measured passes, every pass asserted token-identical.

    The workload is built to have real overlappable host work, not
    just scheduler arithmetic: requests cycle FOUR tenant adapters
    over a pool with three usable pages and two lanes, so in steady
    state the queue head's adapter is never resident — the serial
    engine pays the host->device swap-in inside the admission path's
    `adapter_swap` phase, while the async core prefetches that page
    behind the in-flight step (stage 5 of `_step_async`) and admits
    against a resident hit. Drafter proposals ride the helper thread
    against the admission/prefill work of the same step.

    Reported: per-phase host-gap ms/step and device fraction for both
    modes (median across reps — the CPU runner's step costs are
    ms-scale where machine noise lives). Asserted where measured: the
    async overlappable host gap (schedule + draft_propose +
    adapter_swap) strictly below serial's, async device fraction no
    lower — the ROADMAP item 3 claim."""

    def run_bench():
        import os
        import time

        import numpy as np

        import paddle_tpu  # noqa: F401
        from paddle_tpu.adapters import AdapterRegistry
        from paddle_tpu.inference import GenerationEngine
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        if os.environ.get("PADDLE_SERVE_ASYNC") not in (None, ""):
            # the row IS the serial-vs-async comparison; a global env
            # override would silently collapse both arms to one mode
            raise RuntimeError(
                "unset PADDLE_SERVE_ASYNC to run the async-overlap "
                "row (it builds both modes explicitly)")
        cfg = model_cfg or GPTConfig(
            vocab_size=50304, hidden_size=1024, num_layers=24,
            num_heads=16, max_seq_len=512)
        rng = np.random.RandomState(seed)
        # repeat-heavy prompts from a small alphabet: the NgramDrafter
        # actually matches (and costs real host time) instead of
        # no-op'ing on unrepeated random ids
        alpha = min(64, cfg.vocab_size)
        hi = min(97, cfg.max_seq_len - max_new)
        lo = min(32, hi - 1)
        reqs = [(rng.randint(0, alpha,
                             rng.randint(lo, hi)).astype(np.int32),
                 1 + i % 4)             # cycle adapters 1..4
                for i in range(num_requests)]
        model = GPTForCausalLM(cfg)
        model.eval()

        def registry():
            # four tenants over three usable pages: the steady-state
            # queue head is never resident, every admission pays (or
            # prefetches) a swap-in
            w_rng = np.random.RandomState(7)
            reg = AdapterRegistry(cfg, max_rank=4)
            H, I = cfg.hidden_size, cfg.intermediate_size
            L = cfg.num_layers
            for aid in (1, 2, 3, 4):
                w = {"qkv": [(w_rng.randn(2, H).astype(np.float32)
                              * 0.01,
                              w_rng.randn(3 * H, 2).astype(np.float32)
                              * 0.01)
                             for _ in range(L)]}
                reg.register(aid, w, scaling=0.25)
            return reg

        def build(async_core):
            engine = GenerationEngine(
                model, num_slots=num_slots, block_size=block_size,
                spec_decode_k=spec_k, tracing=True,
                adapters=registry(), adapter_pool_pages=4,
                async_core=async_core)
            if not engine.tracing:
                raise RuntimeError(
                    "bench row requested tracing=True but the engine "
                    "resolved tracing off (is PADDLE_SERVE_TRACING "
                    "set?) — unset it to run this row")
            assert engine.async_core == async_core
            return engine

        def serve(engine):
            t0 = time.perf_counter()
            ids = [engine.add_request(p, max_new_tokens=max_new,
                                      req_id=i, adapter_id=aid)
                   for i, (p, aid) in enumerate(reqs)]
            out = engine.run()
            dt = time.perf_counter() - t0
            return dt, [list(map(int, out[i])) for i in ids]

        def phase_report(engine):
            snap = engine.metrics_snapshot()
            series = snap["engine_step_host_gap_seconds"]["series"]
            per_step, sums = {}, {}
            for s in series:
                if not s["count"]:
                    continue
                ph = s["labels"]["phase"]
                sums[ph] = s["sum"]
                per_step[ph] = round(s["sum"] / s["count"] * 1e3, 4)
            total = sum(sums.values())
            frac = round(sums.get("device_wait", 0.0) / total, 4) \
                if total else 0.0
            overlap = sum(sums.get(p, 0.0) for p in
                          ("schedule", "draft_propose", "adapter_swap"))
            return per_step, frac, overlap

        def median(xs):
            xs = sorted(xs)
            return xs[len(xs) // 2]

        engines = {"serial": build(False), "async": build(True)}
        for eng in engines.values():
            serve(eng)                         # cold: compiles land
        samples = {m: [] for m in engines}
        for rep in range(reps):                # interleaved: machine
            tokens = {}                        # drift hits both modes
            for mode, eng in engines.items():
                eng.metrics.reset()
                dt, tokens[mode] = serve(eng)
                warm, frac, overlap = phase_report(eng)
                samples[mode].append(
                    {"phases": warm, "frac": frac,
                     "overlap_ms": overlap * 1e3, "serve_ms": dt * 1e3})
            assert tokens["async"] == tokens["serial"], \
                f"async core diverged from the serial stream (rep {rep})"
        rec = {}
        for mode, ss in samples.items():
            mid = median([s["overlap_ms"] for s in ss])
            rec[mode] = {
                "phase_ms_per_step_warm":
                    ss[[s["overlap_ms"] for s in ss].index(mid)]
                    ["phases"],
                "device_fraction_warm":
                    median([s["frac"] for s in ss]),
                "host_overlap_gap_ms": round(mid, 3),
                "serve_ms_warm":
                    round(median([s["serve_ms"] for s in ss]), 1),
            }
        # the remaining two gates, asserted where they're measured
        # (stream identity was asserted per rep above): a strictly
        # smaller overlappable host gap, a device fraction that did
        # not regress
        assert rec["async"]["host_overlap_gap_ms"] \
            < rec["serial"]["host_overlap_gap_ms"], (
                "async host gap (schedule+draft_propose+adapter_swap) "
                f"not below serial: {rec['async']} vs {rec['serial']}")
        assert rec["async"]["device_fraction_warm"] \
            >= rec["serial"]["device_fraction_warm"], (
                "async device fraction regressed vs serial: "
                f"{rec['async']} vs {rec['serial']}")
        return {"ms": rec["async"]["serve_ms_warm"], **rec,
                "requests": num_requests, "k": spec_k, "reps": reps}

    return run_bench


def run():
    results = {}
    for name, case in suite().items():
        if callable(case):                 # lazy heavy row: build now
            case = case()
        if isinstance(case, dict):         # self-timed (engine) row
            rec = {"op": name, **case}
        else:
            fn, args, flops = case[:3]
            extra = case[3] if len(case) > 3 else {}
            ms = _timeit(fn, *args)
            rec = {"op": name, "ms": round(ms, 4)}
            if flops:
                rec["tflops"] = round(flops / (ms / 1e3) / 1e12, 2)
            if extra.get("tokens"):
                rec["tokens_per_s"] = round(extra["tokens"] / (ms / 1e3))
        results[name] = rec
        print(json.dumps(rec), flush=True)
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--save", metavar="FILE")
    ap.add_argument("--check", metavar="FILE")
    ap.add_argument("--threshold", type=float, default=0.25,
                    help="allowed fractional slowdown vs baseline")
    args = ap.parse_args()
    results = run()
    if args.save:
        with open(args.save, "w") as f:
            json.dump(results, f, indent=1)
        print(f"baseline saved to {args.save}")
    if args.check:
        with open(args.check) as f:
            base = json.load(f)
        failed = []
        for name, rec in results.items():
            if name in base:
                slow = rec["ms"] / base[name]["ms"] - 1.0
                if slow > args.threshold:
                    failed.append(f"{name}: {slow:+.0%} vs baseline "
                                  f"({rec['ms']}ms vs {base[name]['ms']}ms)")
        # a silently-skipped op is a disabled gate, not a pass
        for name in sorted(set(results) - set(base)):
            failed.append(f"{name}: not in baseline (refresh with --save)")
        for name in sorted(set(base) - set(results)):
            failed.append(f"{name}: in baseline but not measured")
        if failed:
            print("REGRESSION GATE FAILED:\n  " + "\n  ".join(failed))
            sys.exit(1)
        print(f"regression gate ok ({len(results)} ops, "
              f"threshold {args.threshold:.0%})")


if __name__ == "__main__":
    main()
