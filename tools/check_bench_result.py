"""CI bench regression gate — analog of
tools/check_op_benchmark_result.py + tools/ci_model_benchmark.sh: fail
when a model bench row or an op microbench regresses beyond the
threshold vs its stored baseline.

Usage:
    # model rows: current = file of bench.py JSON lines (or '-' stdin)
    python tools/check_bench_result.py --bench current.jsonl \
        --baseline BENCH_BASELINE.json [--threshold 0.10]
    # op rows: delegates to bench_ops result files (op -> {ms})
    python tools/check_bench_result.py --opbench current.json \
        --baseline OPBENCH.json [--threshold 0.25]
    # refresh the model baseline from a current run
    python tools/check_bench_result.py --bench current.jsonl \
        --baseline BENCH_BASELINE.json --update
    # rows the suite produces that the op baseline has never adopted
    python tools/check_bench_result.py --pending OPBENCH.json [--strict]

Model rows compare `value` (throughput: higher is better); op rows
compare `ms` (lower is better). A metric present in the baseline but
missing from the current run fails (a silently-skipped bench is a
disabled gate); new metrics pass with a note (add them with --update).
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def load_bench_lines(path):
    text = sys.stdin.read() if path == "-" else open(path).read()
    rows = {}
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "metric" in rec and "value" in rec:
            rows[rec["metric"]] = rec
    return rows


def check_models(current, baseline, threshold):
    failures, notes = [], []
    for metric, base in baseline.items():
        if metric not in current:
            failures.append(f"{metric}: missing from current run "
                            "(baseline has it)")
            continue
        cur = current[metric]
        if metric.endswith("_FAILED") or cur.get("unit") == "error":
            failures.append(f"{metric}: current run FAILED")
            continue
        bv, cv = float(base["value"]), float(cur["value"])
        if "floor" in base:
            # absolute pass condition replacing the relative check: the
            # row is gated on clearing a decided throughput floor (the
            # ResNet go/no-go call — see DESIGN_DECISIONS.md), not on
            # chasing its own best-ever value
            fv = float(base["floor"])
            if cv < fv:
                failures.append(
                    f"{metric}: {cv:.1f} below the decided floor "
                    f"{fv:.1f} {base.get('unit', '')}".rstrip())
            continue
        if bv <= 0:
            continue
        drop = 1.0 - cv / bv
        if drop > threshold:
            failures.append(
                f"{metric}: {cv:.1f} vs baseline {bv:.1f} "
                f"({drop:+.1%} regression, threshold {threshold:.0%})")
    for metric in sorted(set(current) - set(baseline)):
        notes.append(f"{metric}: not in baseline (add with --update)")
    return failures, notes


def check_ops(current, baseline, threshold):
    failures, notes = [], []
    for name, base in baseline.items():
        if name not in current:
            failures.append(f"{name}: missing from current run")
            continue
        slow = current[name]["ms"] / base["ms"] - 1.0
        if slow > threshold:
            failures.append(
                f"{name}: {current[name]['ms']}ms vs {base['ms']}ms "
                f"({slow:+.0%}, threshold {threshold:.0%})")
    for name in sorted(set(current) - set(baseline)):
        notes.append(f"{name}: not in baseline")
    return failures, notes


def check_pending(baseline_path, suite_names=None, strict=False):
    """Bench rows the suite produces that have NO baseline entry are
    PENDING — they exist in code but the gate cannot see them until a
    TPU `bench_ops.py --save` refresh adopts them (the silent-absence
    failure mode: a new row looks tracked but regresses ungated).
    Also flags stale baseline entries no current row produces."""
    if suite_names is None:
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        import bench_ops

        # names only — suite() would eagerly allocate every case's
        # device inputs just to read the keys
        suite_names = bench_ops.suite_names()
    try:
        with open(baseline_path) as f:
            baseline = json.load(f)
    except FileNotFoundError:
        baseline = {}
    pending = [n for n in suite_names if n not in baseline]
    stale = [n for n in baseline if n not in suite_names]
    for n in pending:
        print(f"PENDING: {n} — in the bench_ops suite but absent from "
              f"{baseline_path}; adopt it with a TPU "
              "`bench_ops.py --save` refresh")
    for n in stale:
        print(f"note: {n}: in {baseline_path} but no suite row "
              "produces it (stale baseline entry)")
    if not pending:
        print(f"no pending rows ({len(suite_names)} suite rows all "
              f"tracked by {baseline_path})")
        return 0
    print(f"{len(pending)} pending row(s) not gated")
    return 1 if strict else 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--bench", help="bench.py JSON-lines file or '-'")
    g.add_argument("--opbench", help="bench_ops.py --save style file")
    g.add_argument("--pending", metavar="OPBENCH",
                   help="list bench_ops suite rows missing from this "
                        "baseline as PENDING (report-only unless "
                        "--strict)")
    ap.add_argument("--strict", action="store_true",
                    help="with --pending: exit 1 when any row is "
                         "pending")
    ap.add_argument("--baseline")
    ap.add_argument("--threshold", type=float, default=None,
                    help="allowed fractional regression "
                         "(default 0.10 model / 0.25 op)")
    ap.add_argument("--update", action="store_true",
                    help="write the current results as the new baseline "
                         "instead of checking")
    args = ap.parse_args(argv)

    if args.pending:
        if args.update or args.baseline or args.threshold is not None:
            ap.error("--pending is report-only; it takes no "
                     "--update/--baseline/--threshold")
        return check_pending(args.pending, strict=args.strict)
    if not args.baseline:
        ap.error("--baseline is required with --bench/--opbench")
    if args.bench:
        current = load_bench_lines(args.bench)
        threshold = 0.10 if args.threshold is None else args.threshold
        if args.update:
            # decided floors are part of the GATE, not of any one run:
            # carry them over so a refresh can't silently drop them —
            # including a floored row the current run didn't emit at
            # all (a partial run must not erase a go/no-go decision)
            try:
                with open(args.baseline) as f:
                    old = json.load(f)
            except (FileNotFoundError, json.JSONDecodeError):
                old = {}
            for k, rec in old.items():
                if "floor" not in rec:
                    continue
                if k in current:
                    current[k]["floor"] = rec["floor"]
                else:
                    current[k] = rec
                    print(f"note: {k}: not in current run; floored "
                          "baseline row kept as-is")
            with open(args.baseline, "w") as f:
                json.dump(current, f, indent=1)
            print(f"baseline updated: {args.baseline} "
                  f"({len(current)} metrics)")
            return 0
        with open(args.baseline) as f:
            baseline = json.load(f)
        failures, notes = check_models(current, baseline, threshold)
    else:
        with open(args.opbench) as f:
            current = json.load(f)
        threshold = 0.25 if args.threshold is None else args.threshold
        if args.update:
            with open(args.baseline, "w") as f:
                json.dump(current, f, indent=1)
            print(f"baseline updated: {args.baseline}")
            return 0
        with open(args.baseline) as f:
            baseline = json.load(f)
        failures, notes = check_ops(current, baseline, threshold)

    for n in notes:
        print(f"note: {n}")
    if failures:
        print("BENCH REGRESSION GATE FAILED:\n  " + "\n  ".join(failures))
        return 1
    print(f"bench gate ok ({len(current)} entries, "
          f"threshold {threshold:.0%})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
