#!/usr/bin/env python
"""tpu-verify — jaxpr/StableHLO trace-contract checker.

Abstractly traces every registered compiled engine program over the
full serving matrix ({dense,pallas} x K in {0,4} x mp in {1,2}) on
CPU — no device execution — and enforces the TPU1xx trace contracts
(donation aliasing, baked constants, accumulation dtype, collective
budget, trace-key stability, host callbacks) plus the committed
TRACE_BASELINE.json drift snapshot.

Usage:
    python tools/tpu_verify.py
    python tools/tpu_verify.py --stats --format=json
    python tools/tpu_verify.py --list-rules
    python tools/tpu_verify.py --write-trace-baseline

See README "Trace verification" for the rule table and contract
declaration etiquette. Runs as a tier-1 gate
(tests/test_tpu_verify_gate.py).
"""
import os
import sys

# abstract tracing on CPU is sufficient (DESIGN_DECISIONS r13) and the
# mp=2 configs need a virtual device mesh — both must be pinned BEFORE
# the first jax backend init
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.analysis.trace.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
