#!/usr/bin/env python
"""tpu-shard — static sharding-layout & per-axis collective-byte
analyzer.

Consumes the tpu-verify harvest (every registered compiled program,
abstractly lowered on CPU over the full serving matrix) and enforces
the TPU3xx sharding contracts: every collective classified by mesh
axis and byte-budgeted against `jit.introspect.GPT_SERVING_AXIS_BUDGET`
(TPU301/TPU304/TPU305), every declared PartitionSpec checked against
the lowered module's actual shardings (TPU302/TPU303), and per-axis
byte totals drift-pinned in the committed SHARD_BASELINE.json
(TPU300).

Usage:
    python tools/tpu_shard.py paddle_tpu/
    python tools/tpu_shard.py --stats --format=json
    python tools/tpu_shard.py --list-rules
    python tools/tpu_shard.py --write-shard-baseline

See README "Sharding analysis" for the rule table and budget
etiquette. Runs as a tier-1 gate (tests/test_tpu_shard_gate.py).
"""
import os
import sys

# abstract tracing on CPU is sufficient (DESIGN_DECISIONS r13) and the
# mp=2 configs need a virtual device mesh — both must be pinned BEFORE
# the first jax backend init
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.analysis.shard.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
