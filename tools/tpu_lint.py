#!/usr/bin/env python
"""tpu-lint — trace-safety & recompile-hazard static analyzer.

Usage:
    python tools/tpu_lint.py paddle_tpu bench_ops.py tools
    python tools/tpu_lint.py --stats --format=json some/file.py
    python tools/tpu_lint.py --list-rules

See README "Static analysis" for the rule table and suppression
etiquette. Runs as a tier-1 gate (tests/test_tpu_lint_gate.py).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
