"""Generate paddle_tpu/ops/ops.yaml from the live op surface — the
op-schema source (analog of paddle/phi/api/yaml/ops.yaml, emitted once
then maintained by hand alongside new ops).

Each entry records: name, module, signature, whether it is installed as
a Tensor method, and its AMP category (white = runs bf16 under
auto_cast, black = pinned fp32, none = follows inputs). The yaml is
AUTHORITATIVE at runtime for the AMP lists and the op registry
(paddle_tpu/ops/registry.py); this script only bootstraps/refreshes it.

    python tools/gen_ops_yaml.py        # rewrites ops/ops.yaml
"""
import inspect
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import yaml  # noqa: E402

import paddle_tpu  # noqa: E402
import importlib  # noqa: E402

# the package rebinds the name `auto_cast` to the function; fetch the
# MODULE from sys.modules via importlib
ac = importlib.import_module("paddle_tpu.amp.auto_cast")  # noqa: E402
from paddle_tpu.core.tensor import Tensor  # noqa: E402
from paddle_tpu.ops import (activation, creation, linalg, manipulation,  # noqa
                            math, nn_ops, random_ops, reduction)

MODULES = {
    "math": math, "creation": creation, "manipulation": manipulation,
    "reduction": reduction, "linalg": linalg, "activation": activation,
    "random_ops": random_ops, "nn_ops": nn_ops,
}


def main():
    # the yaml is the policy's source of truth: a refresh PRESERVES the
    # existing schema's amp fields (new ops default to 'none') instead
    # of round-tripping through the runtime lists it feeds
    out = os.path.join(REPO, "paddle_tpu", "ops", "ops.yaml")
    prev_amp, prev_extra = {}, None
    if os.path.exists(out):
        with open(out) as f:
            prev = yaml.safe_load(f) or {}
        prev_amp = {e["op"]: e.get("amp", "none")
                    for e in prev.get("ops", [])}
        prev_extra = prev.get("amp_extra")
    white = set(ac.WHITE_LIST)
    black = set(ac.BLACK_LIST)
    entries = []
    for mod_name, mod in MODULES.items():
        for name in getattr(mod, "__all__", []):
            fn = getattr(mod, name)
            if not callable(fn):
                continue
            try:
                sig = str(inspect.signature(fn))
            except (TypeError, ValueError):
                sig = "(...)"
            amp = prev_amp.get(name) if name in prev_amp else (
                "white" if name in white else
                "black" if name in black else "none")
            entries.append({
                "op": name,
                "module": mod_name,
                "signature": sig,
                "tensor_method": callable(getattr(Tensor, name, None)),
                "amp": amp,
            })
    entries.sort(key=lambda e: (e["module"], e["op"]))
    public = {e["op"] for e in entries}
    # AMP policies for dispatch-time-only names (fused/internal ops that
    # aren't public functions: sdpa, mm, the s2d stem, loss internals...)
    doc = {
        "ops": entries,
        "amp_extra": prev_extra if prev_extra is not None else {
            "white": sorted(white - public),
            "black": sorted(black - public),
        },
    }
    with open(out, "w") as f:
        f.write(
            "# Op schema — analog of paddle/phi/api/yaml/ops.yaml.\n"
            "# AUTHORITATIVE for the AMP white/black lists and the op\n"
            "# registry (ops/registry.py loads this at import). Refresh\n"
            "# with tools/gen_ops_yaml.py after adding ops; the registry\n"
            "# test fails if code and schema drift.\n")
        yaml.safe_dump(doc, f, sort_keys=False, width=100)
    print(f"wrote {len(entries)} ops -> {out}")


if __name__ == "__main__":
    main()
