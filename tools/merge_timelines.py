"""Merge per-host/per-rank chrome traces into one timeline — analog of
the reference's tools/CrossStackProfiler/ (multi-node timeline merge).

Each input is a chrome-trace JSON written by
paddle_tpu.profiler.Profiler.export (or jax's trace viewer dump). The
merge namespaces every input's pids (chrome dedupes colliding pids
across hosts, silently interleaving unrelated processes) and labels
them with process_name metadata so the trace viewer shows one row group
per rank.

    python tools/merge_timelines.py -o merged.json \
        rank0/trace.json rank1/trace.json
    python tools/merge_timelines.py -o merged.json 'profiles/*.json'
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def load_events(path):
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):  # bare event-array flavor
        return doc
    return doc.get("traceEvents", [])


def merge(paths, align_start=False):
    merged = []
    for slot, path in enumerate(paths):
        events = load_events(path)
        label = os.path.splitext(os.path.basename(path))[0]
        # per-input pid namespace: slot*100000 + original pid % 100000
        base = (slot + 1) * 100000
        pids = {}
        t0 = min((e["ts"] for e in events if "ts" in e), default=0)
        for e in events:
            e = dict(e)
            if "pid" in e:
                pid = e["pid"]
                if pid not in pids:
                    pids[pid] = base + (len(pids) % 100000)
                e["pid"] = pids[pid]
            if align_start and "ts" in e:
                e["ts"] = e["ts"] - t0
            merged.append(e)
        for orig, new in pids.items():
            merged.append({"name": "process_name", "ph": "M", "pid": new,
                           "args": {"name": f"{label} (pid {orig})"}})
    return merged


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("traces", nargs="+",
                    help="trace files or globs, one per rank/host")
    ap.add_argument("-o", "--output", required=True)
    ap.add_argument("--align-start", action="store_true",
                    help="shift every input so its first event is t=0 "
                         "(hosts without synced clocks)")
    args = ap.parse_args(argv)
    paths = []
    for pat in args.traces:
        hits = sorted(glob.glob(pat))
        paths.extend(hits if hits else [pat])
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        sys.exit(f"trace file(s) not found: {missing}")
    events = merge(paths, align_start=args.align_start)
    with open(args.output, "w") as f:
        json.dump({"traceEvents": events}, f)
    print(f"merged {len(paths)} traces ({len(events)} events) "
          f"-> {args.output}")


if __name__ == "__main__":
    main()
