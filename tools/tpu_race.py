#!/usr/bin/env python
"""tpu-race — static thread-safety & allocator-lifetime analyzer.

Usage:
    python tools/tpu_race.py paddle_tpu bench_ops.py tools
    python tools/tpu_race.py --stats --format=json some/file.py
    python tools/tpu_race.py --list-rules

See README "Race analysis" for the rule table, the guarded-by
annotation etiquette, and the suppression tag. Runs as a tier-1 gate
(tests/test_tpu_race_gate.py).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.analysis.race.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
