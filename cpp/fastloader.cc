// Native batch loader — the C++ runtime piece of the input pipeline.
//
// Reference analog: the C++ DataLoader core (paddle/fluid/framework/
// data_feed.cc, reader/buffered_reader.cc): batch assembly and shuffling
// run in native worker threads, overlapping with Python/JAX work instead
// of fighting the GIL. Python keeps the policy (datasets, transforms);
// this keeps the mechanism: gather rows of a contiguous array into
// batch buffers, prefetched into a bounded queue.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this toolchain):
//   fl_create(data, n_items, item_bytes, batch, drop_last, shuffle,
//             seed, prefetch, workers) -> handle
//   fl_next(handle, out_buf, out_count) -> 1 ok / 0 epoch end
//   fl_epoch(handle)   — reshuffle + restart
//   fl_destroy(handle)
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <queue>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<uint8_t> buf;
  int64_t count = 0;
  int64_t seq = 0;
};

struct Loader {
  const uint8_t* data;
  int64_t n_items, item_bytes, batch;
  bool drop_last, shuffle;
  uint64_t seed;
  int64_t prefetch;
  int n_workers;

  std::vector<int64_t> order;
  std::atomic<int64_t> next_batch_idx{0};  // claimed by workers
  int64_t n_batches = 0;
  int64_t epoch = 0;

  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  // min-heap on seq so batches come out in deterministic order even
  // with racing workers
  struct Cmp {
    bool operator()(const Batch* a, const Batch* b) const {
      return a->seq > b->seq;
    }
  };
  std::priority_queue<Batch*, std::vector<Batch*>, Cmp> ready;
  int64_t next_out_seq = 0;
  int64_t inflight = 0;
  int64_t building = 0;  // workers between claim and push
  bool stopping = false;

  std::vector<std::thread> workers;

  void shuffle_order() {
    order.resize(n_items);
    for (int64_t i = 0; i < n_items; i++) order[i] = i;
    if (shuffle) {
      std::mt19937_64 rng(seed + static_cast<uint64_t>(epoch));
      std::shuffle(order.begin(), order.end(), rng);
    }
    int64_t full = n_items / batch;
    n_batches = drop_last ? full : (n_items + batch - 1) / batch;
  }

  void worker() {
    for (;;) {
      int64_t bi;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_put.wait(lk, [&] { return stopping || inflight < prefetch; });
        if (stopping) return;
        bi = next_batch_idx.load();
        if (bi >= n_batches) {
          cv_get.notify_all();
          // park until the next epoch resets next_batch_idx
          cv_put.wait(lk, [&] {
            return stopping || next_batch_idx.load() < n_batches;
          });
          if (stopping) return;
          continue;
        }
        // claim under the mutex so new_epoch() can quiesce by halting
        // claims and waiting for building == 0
        next_batch_idx.store(bi + 1);
        inflight++;
        building++;
      }
      auto* b = new Batch;
      int64_t start = bi * batch;
      int64_t cnt = std::min(batch, n_items - start);
      b->count = cnt;
      b->seq = bi;
      b->buf.resize(static_cast<size_t>(cnt) * item_bytes);
      for (int64_t r = 0; r < cnt; r++) {
        std::memcpy(b->buf.data() + r * item_bytes,
                    data + order[start + r] * item_bytes,
                    static_cast<size_t>(item_bytes));
      }
      {
        std::unique_lock<std::mutex> lk(mu);
        ready.push(b);
        building--;
        cv_get.notify_all();
      }
    }
  }

  int next(uint8_t* out, int64_t* out_count) {
    std::unique_lock<std::mutex> lk(mu);
    if (next_out_seq >= n_batches) return 0;  // epoch complete
    cv_get.wait(lk, [&] {
      return stopping ||
             (!ready.empty() && ready.top()->seq == next_out_seq);
    });
    if (stopping) return 0;
    Batch* b = ready.top();
    ready.pop();
    inflight--;
    next_out_seq++;
    cv_put.notify_all();
    lk.unlock();
    std::memcpy(out, b->buf.data(), b->buf.size());
    *out_count = b->count;
    delete b;
    return 1;
  }

  void new_epoch() {
    std::unique_lock<std::mutex> lk(mu);
    // quiesce: halt new claims, wait for mid-build workers to finish
    // (they read `order`, which shuffle_order() is about to rewrite,
    // and would otherwise push stale-seq batches after the drain)
    next_batch_idx.store(n_batches);
    cv_put.notify_all();
    cv_get.wait(lk, [&] { return stopping || building == 0; });
    while (!ready.empty()) {
      delete ready.top();
      ready.pop();
    }
    epoch++;
    inflight = 0;
    next_out_seq = 0;
    shuffle_order();
    next_batch_idx.store(0);
    cv_put.notify_all();
  }

  void stop() {
    {
      std::unique_lock<std::mutex> lk(mu);
      stopping = true;
      cv_put.notify_all();
      cv_get.notify_all();
    }
    for (auto& t : workers) t.join();
    std::unique_lock<std::mutex> lk(mu);
    while (!ready.empty()) {
      delete ready.top();
      ready.pop();
    }
  }
};

}  // namespace

extern "C" {

void* fl_create(const void* data, int64_t n_items, int64_t item_bytes,
                int64_t batch, int drop_last, int shuffle, uint64_t seed,
                int64_t prefetch, int workers) {
  auto* L = new Loader;
  L->data = static_cast<const uint8_t*>(data);
  L->n_items = n_items;
  L->item_bytes = item_bytes;
  L->batch = batch;
  L->drop_last = drop_last != 0;
  L->shuffle = shuffle != 0;
  L->seed = seed;
  L->prefetch = prefetch < 1 ? 1 : prefetch;
  L->n_workers = workers < 1 ? 1 : workers;
  L->shuffle_order();
  for (int i = 0; i < L->n_workers; i++)
    L->workers.emplace_back([L] { L->worker(); });
  return L;
}

int64_t fl_num_batches(void* h) { return static_cast<Loader*>(h)->n_batches; }

int fl_next(void* h, void* out, int64_t* out_count) {
  return static_cast<Loader*>(h)->next(static_cast<uint8_t*>(out),
                                       out_count);
}

void fl_epoch(void* h) { static_cast<Loader*>(h)->new_epoch(); }

void fl_destroy(void* h) {
  auto* L = static_cast<Loader*>(h);
  L->stop();
  delete L;
}

}  // extern "C"
