"""Component-level timing to find the MFU gap on the flagship bench.

All timing is lax.scan-based (K iterations inside ONE jitted program,
single dispatch, one readback) because per-dispatch latency through the
axon tunnel is hundreds of ms. Not part of the public bench surface.
"""
from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

K = 10  # scan iterations per measurement


def scan_time(body, init_carry, n=K, label=""):
    """body: carry -> carry. Times n iterations inside one program."""

    def scanned(c):
        def step(c, _):
            return body(c), ()

        c, _ = jax.lax.scan(step, c, None, length=n)
        return c

    f = jax.jit(scanned)
    out = f(init_carry)  # compile + run
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])  # sync
    t0 = time.time()
    out = f(init_carry)
    np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    dt = (time.time() - t0) / n
    del out
    return dt


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    print("backend:", jax.default_backend())

    if which in ("all", "matmul"):
        m = 4096
        a = jnp.ones((m, m), jnp.bfloat16)

        dt = scan_time(lambda c: (c @ c).astype(jnp.bfloat16), a)
        fl = 2 * m**3
        print(f"matmul {m}: {dt*1e3:.2f} ms, {fl/dt/1e12:.1f} TF/s "
              f"({fl/dt/197e12*100:.0f}% of peak)")

    if which in ("all", "attn"):
        B, S, H, D = 2, 2048, 16, 128
        from paddle_tpu.ops.pallas.flash_attention import (
            flash_attention, _xla_attention, PATH_STATS)

        att_fwd = 4 * B * H * S * S * D

        q0 = jnp.ones((B, S, H, D), jnp.bfloat16)
        dt = scan_time(lambda q: flash_attention(q, q, q, causal=True), q0)
        print(f"flash fwd: {dt*1e3:.2f} ms ({att_fwd/dt/1e12:.1f} TF/s)")

        def fb_flash(q):
            return jax.grad(lambda q: jnp.sum(
                flash_attention(q, q, q, causal=True).astype(jnp.float32)))(q)

        dt = scan_time(fb_flash, q0)
        print(f"flash fwd+bwd: {dt*1e3:.2f} ms ({3*att_fwd/dt/1e12:.1f} TF/s) "
              f"stats={PATH_STATS}")

        def fb_dense(q):
            def loss(q):
                qh = jnp.swapaxes(q, 1, 2)
                return jnp.sum(_xla_attention(qh, qh, qh, True, 0.0884).astype(jnp.float32))
            return jax.grad(loss)(q)

        dt = scan_time(fb_dense, q0)
        print(f"dense fwd+bwd: {dt*1e3:.2f} ms ({3*att_fwd/dt/1e12:.1f} TF/s)")

    if which in ("all", "model", "fwd"):
        import paddle_tpu as paddle
        import paddle_tpu.jit as jit
        from paddle_tpu.core import random as random_mod
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.jit.api import build_step_fn
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=32768, hidden_size=2048, num_layers=24,
                        num_heads=16, max_seq_len=2048, dropout=0.0)
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        model.eval()
        model.to(dtype="bfloat16")
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        step = jit.TrainStep(model, opt, model.loss_fn)
        params = [p._array for p in step._params]
        ids = jnp.asarray(np.random.randint(0, cfg.vocab_size, (2, 2048), np.int32))
        rng = jax.random.PRNGKey(0)
        tok = 2 * 2048
        fl_tok = model.flops_per_token(2048)
        ideal = tok * fl_tok / 197e12

        def fwd_loss(param_arrays, inputs, label, rng):
            originals = [p._array for p in step._params]
            try:
                for p, a in zip(step._params, param_arrays):
                    p._array = a
                with random_mod.key_scope(rng):
                    out = model(Tensor._wrap(inputs))
                    loss = model.loss_fn(out, Tensor._wrap(label))
                return loss._array
            finally:
                for p, o in zip(step._params, originals):
                    p._array = o

        if which == "fwd":
            # fwd only: carry = params (loss folded back in so scan isn't elided)
            def body2(c):
                ps, x = c
                l = fwd_loss(ps, x, x, rng)
                return (ps, x + (l * 0).astype(jnp.int32))

            dt = scan_time(body2, (params, ids))
            print(f"model fwd: {dt*1e3:.1f} ms (ideal fwd ~{ideal/3*1e3:.0f} ms)")

            def body3(c):
                ps, x = c
                l, gs = jax.value_and_grad(fwd_loss)(ps, x, x, rng)
                return (gs, x + (l * 0).astype(jnp.int32))

            dt = scan_time(body3, (params, ids))
            print(f"model fwd+bwd: {dt*1e3:.1f} ms (ideal ~{ideal*1e3:.0f} ms)")

        if which in ("all", "model"):
            step_fn = build_step_fn(model, opt, model.loss_fn, step._params,
                                    step._acc_idx)
            accums = step._gather_accums()
            bufs = step._buf_arrays()
            lr = jnp.asarray(1e-4, jnp.float32)

            def body(c):
                ps, acc, mb, st, x = c
                loss, nps, nacc, nmb = step_fn(ps, acc, mb, lr, st, (x,),
                                               x, rng)
                return (nps, nacc, nmb, st + 1,
                        x + (loss * 0).astype(jnp.int32))

            st = jnp.asarray(0, jnp.int32)
            dt = scan_time(body, (params, accums, bufs, st, ids))
            print(f"full step: {dt*1e3:.1f} ms  mfu={ideal/dt:.3f}  "
                  f"(ideal ~{ideal*1e3:.0f} ms)")


if __name__ == "__main__":
    main()
