"""paddle.vision.datasets analog (python/paddle/vision/datasets/):
MNIST/FashionMNIST (IDX files) and Cifar10/Cifar100 (pickled batches in
a tar). This environment has no egress, so download=True raises; point
image_path/label_path/data_file at local copies (the reference's
cached-file path) — the parsers read the real formats.
"""
from __future__ import annotations

import gzip
import pickle
import tarfile

import numpy as np

from paddle_tpu.io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100"]


def _open_maybe_gz(path):
    if str(path).endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def _read_idx(path, magic_want, header_dims):
    with _open_maybe_gz(path) as f:
        head = np.frombuffer(f.read(4 * (1 + header_dims)), ">u4")
        if head[0] != magic_want:
            raise ValueError(
                f"{path}: bad IDX magic {head[0]:#x}, want {magic_want:#x}")
        dims = tuple(int(d) for d in head[1:])
        data = np.frombuffer(f.read(int(np.prod(dims))), np.uint8)
    return data.reshape(dims)


class MNIST(Dataset):
    """IDX-format MNIST (vision/datasets/mnist.py analog). Items:
    (image [28,28,1] float32 in [0,1] unless backend='raw', label int64).
    """

    _default_mode_files = {}  # no download cache in this environment

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download and (image_path is None or label_path is None):
            raise RuntimeError(
                "no network egress in this environment: pass local "
                "image_path/label_path (IDX files, optionally .gz)")
        assert mode in ("train", "test")
        if image_path is None or label_path is None:
            raise ValueError("image_path and label_path are required")
        self.mode = mode
        self.transform = transform
        self.backend = backend
        self.images = _read_idx(image_path, 0x803, 3)  # [N, 28, 28]
        self.labels = _read_idx(label_path, 0x801, 1)  # [N]
        if len(self.images) != len(self.labels):
            raise ValueError(
                f"images ({len(self.images)}) / labels "
                f"({len(self.labels)}) count mismatch")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, i):
        img = self.images[i][..., None]
        if self.backend != "raw":
            img = img.astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[i])


class FashionMNIST(MNIST):
    """Same IDX container, different content (fashion_mnist.py)."""


class _Cifar(Dataset):
    _batch_names: tuple = ()
    _test_names: tuple = ()
    _label_key = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download and data_file is None:
            raise RuntimeError(
                "no network egress in this environment: pass a local "
                "data_file (the cifar tar.gz)")
        assert mode in ("train", "test")
        if data_file is None:
            raise ValueError("data_file is required")
        self.mode = mode
        self.transform = transform
        self.backend = backend
        names = self._batch_names if mode == "train" else self._test_names
        imgs, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for member in tf.getmembers():
                base = member.name.rsplit("/", 1)[-1]
                if base in names:
                    d = pickle.load(tf.extractfile(member),
                                    encoding="bytes")
                    imgs.append(np.asarray(d[b"data"], np.uint8))
                    labels.extend(int(v) for v in d[self._label_key])
        if not imgs:
            raise ValueError(f"{data_file}: no {mode} batches found")
        self.images = np.concatenate(imgs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, i):
        img = self.images[i].transpose(1, 2, 0)  # HWC like the reference
        if self.backend != "raw":
            img = img.astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[i]


class Cifar10(_Cifar):
    """cifar-10-python.tar.gz: data_batch_1..5 + test_batch
    (vision/datasets/cifar.py analog)."""

    _batch_names = tuple(f"data_batch_{i}" for i in range(1, 6))
    _test_names = ("test_batch",)
    _label_key = b"labels"


class Cifar100(_Cifar):
    """cifar-100-python.tar.gz: train + test, fine labels."""

    _batch_names = ("train",)
    _test_names = ("test",)
    _label_key = b"fine_labels"
