"""Image transforms — analog of python/paddle/vision/transforms/ (host-side
numpy preprocessing; the device never sees un-batched images)."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, x):
        x = np.asarray(x, np.float32)
        if self.data_format == "CHW":
            mean = self.mean.reshape(-1, 1, 1)
            std = self.std.reshape(-1, 1, 1)
        else:
            mean, std = self.mean, self.std
        return (x - mean) / std


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, x):
        x = np.asarray(x, np.float32) / 255.0
        if x.ndim == 2:
            x = x[None]
        elif x.ndim == 3 and self.data_format == "CHW":
            x = x.transpose(2, 0, 1)
        return x


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, x):
        import jax.image
        import jax.numpy as jnp

        arr = jnp.asarray(np.asarray(x, np.float32))
        chw = arr.ndim == 3 and arr.shape[0] <= 4
        if chw:
            out = jax.image.resize(arr, (arr.shape[0],) + self.size, "linear")
        elif arr.ndim == 3:
            out = jax.image.resize(arr, self.size + (arr.shape[2],), "linear")
        else:
            out = jax.image.resize(arr, self.size, "linear")
        return np.asarray(out)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, x):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(np.flip(np.asarray(x), axis=-1))
        return x


class RandomCrop:
    def __init__(self, size, padding=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, x):
        x = np.asarray(x)
        chw = x.ndim == 3 and x.shape[0] <= 4
        h_axis = 1 if chw else 0
        if self.padding:
            p = self.padding
            cfg = [(0, 0)] * x.ndim
            cfg[h_axis] = (p, p)
            cfg[h_axis + 1] = (p, p)
            x = np.pad(x, cfg)
        H, W = x.shape[h_axis], x.shape[h_axis + 1]
        th, tw = self.size
        i = np.random.randint(0, H - th + 1)
        j = np.random.randint(0, W - tw + 1)
        if chw:
            return x[:, i:i + th, j:j + tw]
        return x[i:i + th, j:j + tw]
