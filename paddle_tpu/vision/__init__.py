"""paddle.vision analog (python/paddle/vision/). Models land in
vision/models/; datasets/transforms follow."""
from . import models, transforms

__all__ = ["models", "transforms"]
