"""paddle.vision analog (python/paddle/vision/)."""
from . import datasets, models, transforms

__all__ = ["datasets", "models", "transforms"]
