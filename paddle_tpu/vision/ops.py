"""paddle.vision.ops analog — detection ops (nms, distribute route of
PP-YOLOE-style postprocessing).

Reference analog: python/paddle/vision/ops.py (nms), the NMS kernels
(paddle/phi/kernels/cpu/nms_kernel.cc, gpu/nms_kernel.cu) and
multiclass_nms (phi/kernels/cpu/multiclass_nms3_kernel.cc).

TPU-native design: the core is a FIXED-SHAPE jittable suppressor —
an [N,N] IoU matrix plus a lax.fori_loop greedy selection, returning
[max_out] indices with a validity mask (XLA needs static shapes; the
reference's dynamic-length outputs become a (indices, mask) pair).
The eager `nms()` wrapper trims to the dynamic length for paddle
parity. Class-aware NMS uses the coordinate-offset trick so one fixed
suppressor serves multiclass heads.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["box_iou", "nms", "multiclass_nms", "nms_fixed"]


def _arr(x):
    return x._array if isinstance(x, Tensor) else jnp.asarray(x)


def box_iou(boxes1, boxes2):
    """IoU matrix [N,M] for xyxy boxes."""
    a, b = _arr(boxes1), _arr(boxes2)

    def fn(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                                   1e-9)

    return Tensor._wrap(fn(a, b))


@functools.partial(jax.jit, static_argnames=("max_out",))
def nms_fixed(boxes, scores, iou_threshold, max_out):
    """Fixed-shape greedy NMS: ([N,4], [N]) ->
    (indices [max_out] int32 (-1 padded), valid [max_out] bool).
    Jittable — usable inside compiled detection heads."""
    n = boxes.shape[0]
    iou = _arr(box_iou(boxes, boxes))
    order_scores = scores

    def body(k, state):
        alive, idxs, valid = state
        masked = jnp.where(alive, order_scores, -jnp.inf)
        best = jnp.argmax(masked)
        ok = masked[best] > -jnp.inf
        idxs = idxs.at[k].set(jnp.where(ok, best, -1))
        valid = valid.at[k].set(ok)
        # suppress the chosen box and its high-IoU neighbours
        suppress = (iou[best] >= iou_threshold) | \
            (jnp.arange(n) == best)
        alive = alive & jnp.where(ok, ~suppress, alive)
        return alive, idxs, valid

    alive0 = jnp.ones((n,), bool)
    idxs0 = jnp.full((max_out,), -1, jnp.int32)
    valid0 = jnp.zeros((max_out,), bool)
    _, idxs, valid = jax.lax.fori_loop(0, max_out, body,
                                       (alive0, idxs0, valid0))
    return idxs, valid


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """paddle.vision.ops.nms parity (eager: returns the kept indices,
    dynamic length). With category_idxs, suppression is per-class
    (coordinate offset trick)."""
    b = _arr(boxes).astype(jnp.float32)
    s = None if scores is None else _arr(scores).astype(jnp.float32)
    cat = None if category_idxs is None \
        else _arr(category_idxs)
    sel = None
    if categories is not None and cat is not None:
        # paddle semantics: suppression runs only over the listed
        # categories; other boxes are excluded from the result
        keep_mask = np.isin(np.asarray(cat), np.asarray(categories))
        sel = np.nonzero(keep_mask)[0]
        b = b[jnp.asarray(sel)]
        cat = cat[jnp.asarray(sel)]
        if s is not None:
            s = s[jnp.asarray(sel)]
    n = b.shape[0]
    if n == 0:
        return Tensor._wrap(jnp.zeros((0,), jnp.int32))
    if s is None:
        s = jnp.arange(n, 0, -1, dtype=jnp.float32)
    if cat is not None:
        span = (b.max() - b.min()) + 1.0
        b = b + (cat.astype(jnp.float32) * span)[:, None]  # no overlap
    # pad N and max_out to power-of-two buckets: box counts are
    # data-dependent, and an exact-N jit would recompile per image
    bucket = 1 << max(int(n - 1).bit_length(), 3)
    if bucket != n:
        b = jnp.concatenate([b, jnp.zeros((bucket - n, 4), b.dtype)])
        s = jnp.concatenate([s, jnp.full((bucket - n,), -jnp.inf,
                                         s.dtype)])
    want = n if top_k is None or int(top_k) < 0 else min(int(top_k), n)
    max_out = 1 << max(int(want - 1).bit_length(), 3)
    idxs, valid = nms_fixed(b, s, jnp.float32(iou_threshold), max_out)
    kept = np.asarray(idxs)[np.asarray(valid)][:want]
    if sel is not None:
        kept = sel[kept]  # map back to original indexing
    return Tensor._wrap(jnp.asarray(kept, jnp.int32))


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.45,
                   background_label=-1):
    """multiclass_nms3 analog for one image: bboxes [N,4],
    scores [C,N] -> (out [K,6] (label, score, x1,y1,x2,y2), K).
    Fixed-shape inner NMS per the TPU design; assembly is eager."""
    b = np.asarray(_arr(bboxes), np.float32)
    sc = np.asarray(_arr(scores), np.float32)
    C, N = sc.shape
    all_boxes, all_scores, all_cats = [], [], []
    for c in range(C):
        if c == background_label:
            continue
        m = sc[c] >= score_threshold
        if not m.any():
            continue
        idx = np.nonzero(m)[0]
        if len(idx) > nms_top_k:
            idx = idx[np.argsort(-sc[c][idx])[:nms_top_k]]
        all_boxes.append(b[idx])
        all_scores.append(sc[c][idx])
        all_cats.append(np.full(len(idx), c, np.int64))
    if not all_boxes:
        return Tensor._wrap(jnp.zeros((0, 6), jnp.float32)), 0
    cb = np.concatenate(all_boxes)
    cs = np.concatenate(all_scores)
    cc = np.concatenate(all_cats)
    kept = np.asarray(nms(cb, nms_threshold, scores=cs, category_idxs=cc,
                          top_k=keep_top_k)._array)
    out = np.concatenate(
        [cc[kept, None].astype(np.float32), cs[kept, None], cb[kept]],
        axis=1)
    order = np.argsort(-out[:, 1])
    out = out[order]
    return Tensor._wrap(jnp.asarray(out)), len(out)
