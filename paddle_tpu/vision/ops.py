"""paddle.vision.ops analog — detection ops (nms, distribute route of
PP-YOLOE-style postprocessing).

Reference analog: python/paddle/vision/ops.py (nms), the NMS kernels
(paddle/phi/kernels/cpu/nms_kernel.cc, gpu/nms_kernel.cu) and
multiclass_nms (phi/kernels/cpu/multiclass_nms3_kernel.cc).

TPU-native design: the core is a FIXED-SHAPE jittable suppressor —
an [N,N] IoU matrix plus a lax.fori_loop greedy selection, returning
[max_out] indices with a validity mask (XLA needs static shapes; the
reference's dynamic-length outputs become a (indices, mask) pair).
The eager `nms()` wrapper trims to the dynamic length for paddle
parity. Class-aware NMS uses the coordinate-offset trick so one fixed
suppressor serves multiclass heads.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["box_iou", "nms", "multiclass_nms", "nms_fixed",
           "roi_align", "deform_conv2d", "box_coder"]


def _arr(x):
    return x._array if isinstance(x, Tensor) else jnp.asarray(x)


def box_iou(boxes1, boxes2):
    """IoU matrix [N,M] for xyxy boxes."""
    a, b = _arr(boxes1), _arr(boxes2)

    def fn(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                                   1e-9)

    return Tensor._wrap(fn(a, b))


@functools.partial(jax.jit, static_argnames=("max_out",))
def nms_fixed(boxes, scores, iou_threshold, max_out):
    """Fixed-shape greedy NMS: ([N,4], [N]) ->
    (indices [max_out] int32 (-1 padded), valid [max_out] bool).
    Jittable — usable inside compiled detection heads."""
    n = boxes.shape[0]
    iou = _arr(box_iou(boxes, boxes))
    order_scores = scores

    def body(k, state):
        alive, idxs, valid = state
        masked = jnp.where(alive, order_scores, -jnp.inf)
        best = jnp.argmax(masked)
        ok = masked[best] > -jnp.inf
        idxs = idxs.at[k].set(jnp.where(ok, best, -1))
        valid = valid.at[k].set(ok)
        # suppress the chosen box and its high-IoU neighbours
        suppress = (iou[best] >= iou_threshold) | \
            (jnp.arange(n) == best)
        alive = alive & jnp.where(ok, ~suppress, alive)
        return alive, idxs, valid

    alive0 = jnp.ones((n,), bool)
    idxs0 = jnp.full((max_out,), -1, jnp.int32)
    valid0 = jnp.zeros((max_out,), bool)
    _, idxs, valid = jax.lax.fori_loop(0, max_out, body,
                                       (alive0, idxs0, valid0))
    return idxs, valid


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """paddle.vision.ops.nms parity (eager: returns the kept indices,
    dynamic length). With category_idxs, suppression is per-class
    (coordinate offset trick)."""
    b = _arr(boxes).astype(jnp.float32)
    s = None if scores is None else _arr(scores).astype(jnp.float32)
    cat = None if category_idxs is None \
        else _arr(category_idxs)
    sel = None
    if categories is not None and cat is not None:
        # paddle semantics: suppression runs only over the listed
        # categories; other boxes are excluded from the result
        keep_mask = np.isin(np.asarray(cat), np.asarray(categories))
        sel = np.nonzero(keep_mask)[0]
        b = b[jnp.asarray(sel)]
        cat = cat[jnp.asarray(sel)]
        if s is not None:
            s = s[jnp.asarray(sel)]
    n = b.shape[0]
    if n == 0:
        return Tensor._wrap(jnp.zeros((0,), jnp.int32))
    if s is None:
        s = jnp.arange(n, 0, -1, dtype=jnp.float32)
    if cat is not None:
        span = (b.max() - b.min()) + 1.0
        b = b + (cat.astype(jnp.float32) * span)[:, None]  # no overlap
    # pad N and max_out to power-of-two buckets: box counts are
    # data-dependent, and an exact-N jit would recompile per image
    bucket = 1 << max(int(n - 1).bit_length(), 3)
    if bucket != n:
        b = jnp.concatenate([b, jnp.zeros((bucket - n, 4), b.dtype)])
        s = jnp.concatenate([s, jnp.full((bucket - n,), -jnp.inf,
                                         s.dtype)])
    want = n if top_k is None or int(top_k) < 0 else min(int(top_k), n)
    max_out = 1 << max(int(want - 1).bit_length(), 3)
    idxs, valid = nms_fixed(b, s, jnp.float32(iou_threshold), max_out)
    kept = np.asarray(idxs)[np.asarray(valid)][:want]
    if sel is not None:
        kept = sel[kept]  # map back to original indexing
    return Tensor._wrap(jnp.asarray(kept, jnp.int32))


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.45,
                   background_label=-1):
    """multiclass_nms3 analog for one image: bboxes [N,4],
    scores [C,N] -> (out [K,6] (label, score, x1,y1,x2,y2), K).
    Fixed-shape inner NMS per the TPU design; assembly is eager."""
    b = np.asarray(_arr(bboxes), np.float32)
    sc = np.asarray(_arr(scores), np.float32)
    C, N = sc.shape
    all_boxes, all_scores, all_cats = [], [], []
    for c in range(C):
        if c == background_label:
            continue
        m = sc[c] >= score_threshold
        if not m.any():
            continue
        idx = np.nonzero(m)[0]
        if len(idx) > nms_top_k:
            idx = idx[np.argsort(-sc[c][idx])[:nms_top_k]]
        all_boxes.append(b[idx])
        all_scores.append(sc[c][idx])
        all_cats.append(np.full(len(idx), c, np.int64))
    if not all_boxes:
        return Tensor._wrap(jnp.zeros((0, 6), jnp.float32)), 0
    cb = np.concatenate(all_boxes)
    cs = np.concatenate(all_scores)
    cc = np.concatenate(all_cats)
    kept = np.asarray(nms(cb, nms_threshold, scores=cs, category_idxs=cc,
                          top_k=keep_top_k)._array)
    out = np.concatenate(
        [cc[kept, None].astype(np.float32), cs[kept, None], cb[kept]],
        axis=1)
    order = np.argsort(-out[:, 1])
    out = out[order]
    return Tensor._wrap(jnp.asarray(out)), len(out)


# ---------------------------------------------------------------------------
# RoI / deformable ops (detection model zoo tier)
# ---------------------------------------------------------------------------

def _bilinear_sample(feat, ys, xs):
    """feat [C,H,W], ys/xs [P] float coords -> [C,P]. Out-of-bounds
    samples contribute 0 (roi_align border semantics)."""
    C, H, W = feat.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0
    out = 0.0
    for dy, wy in ((0, 1 - wy1), (1, wy1)):
        for dx, wx in ((0, 1 - wx1), (1, wx1)):
            yy = (y0 + dy).astype(jnp.int32)
            xx = (x0 + dx).astype(jnp.int32)
            valid = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
            yc = jnp.clip(yy, 0, H - 1)
            xc = jnp.clip(xx, 0, W - 1)
            v = feat[:, yc, xc]  # [C,P] gather
            out = out + v * (wy * wx * valid)[None, :]
    return out


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (python/paddle/vision/ops.py roi_align; kernel
    phi/kernels/gpu/roi_align_kernel.cu). x [B,C,H,W] NCHW, boxes
    [K,4] (x1,y1,x2,y2), boxes_num [B]. Returns [K,C,ph,pw].

    TPU-native: fully vectorized — per-roi sample grids, one batched
    bilinear gather vmapped over rois; sampling_ratio<=0 resolves to 2
    (static shapes; the reference's adaptive ceil(roi/bin) is
    data-dependent and cannot be a static shape)."""
    from paddle_tpu.ops.dispatch import apply, as_tensor

    ba = _arr(boxes).astype(jnp.float32)
    bn = _arr(boxes_num).astype(jnp.int32)
    ph, pw = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))
    s = 2 if sampling_ratio is None or sampling_ratio <= 0 \
        else int(sampling_ratio)
    K = ba.shape[0]
    # roi k belongs to image searchsorted(cumsum(bn), k, 'right')
    batch_of = jnp.searchsorted(jnp.cumsum(bn), jnp.arange(K), side="right")
    off = 0.5 if aligned else 0.0

    def fn(xarr):
        xf = xarr.astype(jnp.float32)

        def one_roi(box, bidx):
            x1, y1, x2, y2 = box * spatial_scale
            x1, y1 = x1 - off, y1 - off
            x2, y2 = x2 - off, y2 - off
            rw = x2 - x1
            rh = y2 - y1
            if not aligned:
                rw = jnp.maximum(rw, 1.0)
                rh = jnp.maximum(rh, 1.0)
            bw = rw / pw
            bh = rh / ph
            # sample grid: (ph*s, pw*s) points, s per bin per axis
            gy = y1 + (jnp.arange(ph * s) + 0.5) * \
                (bh / s).astype(jnp.float32)
            gx = x1 + (jnp.arange(pw * s) + 0.5) * \
                (bw / s).astype(jnp.float32)
            yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
            vals = _bilinear_sample(xf[bidx], yy.ravel(), xx.ravel())
            C = vals.shape[0]
            # average the s*s samples of each bin
            return vals.reshape(C, ph, s, pw, s).mean(axis=(2, 4))

        return jax.vmap(one_roi)(ba, batch_of).astype(xarr.dtype)

    # gradients flow to x (bilinear sampling is piecewise-linear);
    # boxes/boxes_num are data, not differentiable inputs
    return apply("roi_align", fn, as_tensor(x))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (python/paddle/vision/ops.py deform_conv2d;
    kernel phi/kernels/gpu/deformable_conv_kernel.cu). x [B,Cin,H,W],
    offset [B, 2*dg*kh*kw, Ho, Wo] (y,x interleaved per tap), mask
    [B, dg*kh*kw, Ho, Wo] for v2. Returns [B,Cout,Ho,Wo].

    TPU-native: gather-based — sample every (tap, output-position) by
    bilinear interpolation (one big vmapped gather), then contract taps
    x channels with the weight in a single einsum on the MXU (the
    im2col-with-offsets formulation). Differentiable in x, offset,
    weight, mask, and bias (routed through the op tape)."""
    from paddle_tpu.ops.dispatch import apply, as_tensor

    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    diff_in = [as_tensor(x), as_tensor(offset), as_tensor(weight)]
    has_mask = mask is not None
    if has_mask:
        diff_in.append(as_tensor(mask))
    has_bias = bias is not None
    if has_bias:
        diff_in.append(as_tensor(bias))

    def fn(*arrs):
        return _deform_conv2d_impl(arrs, has_mask, has_bias, st, pd, dl,
                                   deformable_groups, groups)

    return apply("deform_conv2d", fn, *diff_in)


def _deform_conv2d_impl(arrs, has_mask, has_bias, st, pd, dl,
                        deformable_groups, groups):
    it = iter(arrs)
    xin = next(it)
    xa = xin.astype(jnp.float32)
    oa = next(it).astype(jnp.float32)
    wa = next(it).astype(jnp.float32)
    ma = next(it).astype(jnp.float32) if has_mask else None
    bia = next(it) if has_bias else None
    B, Cin, H, W = xa.shape
    Cout, Cin_g, kh, kw = wa.shape
    Ho = (H + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
    Wo = (W + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
    dg = deformable_groups
    if groups != 1:
        raise NotImplementedError("deform_conv2d: groups>1 not supported")
    if dg != 1 and Cin % dg:
        raise ValueError("Cin not divisible by deformable_groups")

    # base sampling positions per output pixel and tap
    oy = jnp.arange(Ho) * st[0] - pd[0]
    ox = jnp.arange(Wo) * st[1] - pd[1]
    ky = jnp.arange(kh) * dl[0]
    kx = jnp.arange(kw) * dl[1]
    base_y = oy[:, None, None, None] + ky[None, None, :, None]  # Ho,1,kh,1
    base_x = ox[None, :, None, None] + kx[None, None, None, :]  # 1,Wo,1,kw
    base_y = jnp.broadcast_to(base_y, (Ho, Wo, kh, kw)).astype(jnp.float32)
    base_x = jnp.broadcast_to(base_x, (Ho, Wo, kh, kw)).astype(jnp.float32)

    off_r = oa.reshape(B, dg, kh * kw, 2, Ho, Wo)
    dy = jnp.moveaxis(off_r[:, :, :, 0], (2,), (4,)) \
        .reshape(B, dg, Ho, Wo, kh * kw)
    dx = jnp.moveaxis(off_r[:, :, :, 1], (2,), (4,)) \
        .reshape(B, dg, Ho, Wo, kh * kw)
    sy = base_y.reshape(Ho, Wo, kh * kw)[None, None] + dy
    sx = base_x.reshape(Ho, Wo, kh * kw)[None, None] + dx  # B,dg,Ho,Wo,T

    cg = Cin // dg

    def sample_img(feat_g, ys, xs):
        # feat_g [cg,H,W]; ys/xs [Ho,Wo,T]
        return _bilinear_sample(feat_g, ys.ravel(), xs.ravel()) \
            .reshape(cg, Ho, Wo, kh * kw)

    def per_batch(feat, ys, xs, mk):
        # feat [Cin,H,W] -> [dg,cg,H,W]; ys/xs [dg,Ho,Wo,T]
        fg = feat.reshape(dg, cg, H, W)
        vals = jax.vmap(sample_img)(fg, ys, xs)  # [dg,cg,Ho,Wo,T]
        if mk is not None:
            vals = vals * mk.reshape(dg, kh * kw, Ho, Wo) \
                .transpose(0, 2, 3, 1)[:, None]
        return vals.reshape(Cin, Ho, Wo, kh * kw)

    if ma is None:
        vals = jax.vmap(lambda f, ys, xs: per_batch(f, ys, xs, None))(
            xa, sy, sx)
    else:
        vals = jax.vmap(per_batch)(xa, sy, sx, ma)
    # contract (Cin, taps) with weight on the MXU
    wflat = wa.reshape(Cout, Cin, kh * kw)
    out = jnp.einsum("bchwt,oct->bohw", vals, wflat)
    if bia is not None:
        out = out + bia.astype(out.dtype).reshape(1, -1, 1, 1)
    return out.astype(xin.dtype)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode detection boxes against priors
    (python/paddle/vision/ops.py box_coder; phi box_coder kernel).
    encode: [T,4] targets vs [P,4] priors -> [T,P,4] offsets;
    decode: [T,P,4] (or broadcastable) offsets -> boxes."""
    pb = _arr(prior_box).astype(jnp.float32)
    tb = _arr(target_box).astype(jnp.float32)
    pv = None if prior_box_var is None else \
        _arr(prior_box_var).astype(jnp.float32)
    pw = pb[:, 2] - pb[:, 0] + (0.0 if box_normalized else 1.0)
    ph = pb[:, 3] - pb[:, 1] + (0.0 if box_normalized else 1.0)
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + (0.0 if box_normalized else 1.0)
        th = tb[:, 3] - tb[:, 1] + (0.0 if box_normalized else 1.0)
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        ox = (tcx[:, None] - pcx[None]) / pw[None]
        oy = (tcy[:, None] - pcy[None]) / ph[None]
        ow = jnp.log(jnp.maximum(tw[:, None] / pw[None], 1e-10))
        oh = jnp.log(jnp.maximum(th[:, None] / ph[None], 1e-10))
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        if pv is not None:
            out = out / pv[None]
        return Tensor._wrap(out)
    if code_type == "decode_center_size":
        if tb.ndim == 2:
            tb = tb[:, None, :]
        # variance broadcasts along the prior axis (dim 1 for axis=0,
        # dim 0 for axis=1), like the center/size terms below
        if pv is not None:
            o = tb * (pv[None] if axis == 0 else pv[:, None])
        else:
            o = tb
        if axis == 0:
            cw, ch, ccx, ccy = pw[None], ph[None], pcx[None], pcy[None]
        else:
            cw, ch, ccx, ccy = pw[:, None], ph[:, None], pcx[:, None], \
                pcy[:, None]
        dcx = o[..., 0] * cw + ccx
        dcy = o[..., 1] * ch + ccy
        dw = jnp.exp(o[..., 2]) * cw
        dh = jnp.exp(o[..., 3]) * ch
        sub = 0.0 if box_normalized else 1.0
        out = jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                         dcx + dw * 0.5 - sub, dcy + dh * 0.5 - sub],
                        axis=-1)
        return Tensor._wrap(jnp.squeeze(out, 1) if out.shape[1] == 1
                            and _arr(target_box).ndim == 2 else out)
    raise ValueError(f"unknown code_type {code_type!r}")
