"""MobileNetV2 — analog of python/paddle/vision/models/mobilenetv2.py
(inverted residuals, Sandler et al. 2018). Depthwise convs lower to
grouped lax convs; trains through jit.TrainStep in bf16."""
from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = ["MobileNetV2", "mobilenet_v2"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def _conv_bn(cin, cout, k, stride=1, groups=1):
    return nn.Sequential(
        nn.Conv2D(cin, cout, k, stride=stride, padding=(k - 1) // 2,
                  groups=groups, bias_attr=False),
        nn.BatchNorm2D(cout),
        nn.ReLU6(),
    )


class InvertedResidual(nn.Layer):
    def __init__(self, cin, cout, stride, expand_ratio):
        super().__init__()
        hidden = int(round(cin * expand_ratio))
        self.use_res = stride == 1 and cin == cout
        steps = []
        if expand_ratio != 1:
            steps.append(_conv_bn(cin, hidden, 1))
        steps.append(_conv_bn(hidden, hidden, 3, stride, groups=hidden))
        steps.append(nn.Conv2D(hidden, cout, 1, bias_attr=False))
        steps.append(nn.BatchNorm2D(cout))
        self.conv = nn.Sequential(*steps)

    def forward(self, x):
        y = self.conv(x)
        return x + y if self.use_res else y


class MobileNetV2(nn.Layer):
    # t (expansion), c (channels), n (repeats), s (first stride)
    CFG = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
           (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cin = _make_divisible(32 * scale)
        last = _make_divisible(1280 * max(1.0, scale))
        feats = [_conv_bn(3, cin, 3, stride=2)]
        for t, c, n, s in self.CFG:
            cout = _make_divisible(c * scale)
            for i in range(n):
                feats.append(InvertedResidual(cin, cout,
                                              s if i == 0 else 1, t))
                cin = cout
        feats.append(_conv_bn(cin, last, 1))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last, num_classes))
        self._last = last

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = self.classifier(x.reshape([x.shape[0], -1]))
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("no pretrained weights in this build")
    return MobileNetV2(scale=scale, **kwargs)
