"""ResNet — analog of python/paddle/vision/models/resnet.py (the
PaddleClas ResNet-50 benchmark config, BASELINE.md). NCHW, BN layers;
trains through jit.TrainStep on the MXU in bf16 via amp.auto_cast.

The residual blocks are built from `nn.ConvBNReLU` (nn/fused.py)
behind the `conv_backend` seam (`auto`/`dense`/`pallas`, env
`PADDLE_CONV_BACKEND` wins) — the custom conv suite the ResNet MFU
plateau called for. On a pallas-resolved block BOTH modes fuse: EVAL
runs each conv+BN+ReLU as ONE folded-affine Pallas kernel, and
TRAINING runs the batch-stat custom_vjp op (stats fused into the
conv epilogue forward; fused dInput/dWeight kernels backward), so a
resnet50 train step dispatches all 52 bottleneck/downsample convs
through the fused path. Dense-resolved blocks keep byte-for-byte the
old conv -> BN -> ReLU composition in both modes. The 7x7/s2 stem
keeps the space-to-depth trick and stays a plain conv/BN pair (the
fused suite covers the 1x1/3x3 bottleneck shapes; the stem resolves
`dense` cleanly)."""
from __future__ import annotations

import paddle_tpu.nn as nn


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 conv_backend=None):
        super().__init__()
        self.convbn1 = nn.ConvBNReLU(
            inplanes, planes, 3, stride=stride, padding=1, act="relu",
            backend=conv_backend, norm_layer=norm_layer)
        self.convbn2 = nn.ConvBNReLU(
            planes, planes, 3, padding=1, act=None,
            backend=conv_backend, norm_layer=norm_layer)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.convbn2(self.convbn1(x))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 conv_backend=None):
        super().__init__()
        width = int(planes * (base_width / 64.0)) * groups
        self.convbn1 = nn.ConvBNReLU(
            inplanes, width, 1, act="relu", backend=conv_backend,
            norm_layer=norm_layer)
        self.convbn2 = nn.ConvBNReLU(
            width, width, 3, stride=stride, padding=dilation,
            dilation=dilation, groups=groups, act="relu",
            backend=conv_backend, norm_layer=norm_layer)
        self.convbn3 = nn.ConvBNReLU(
            width, planes * self.expansion, 1, act=None,
            backend=conv_backend, norm_layer=norm_layer)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.convbn3(self.convbn2(self.convbn1(x)))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1, conv_backend=None):
        super().__init__()
        layer_cfg = {
            18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
            101: [3, 4, 23, 3], 152: [3, 8, 36, 3],
        }
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._norm_layer = nn.BatchNorm2D
        self._conv_backend = conv_backend
        self.inplanes = 64
        self.dilation = 1

        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        norm_layer = self._norm_layer
        backend = self._conv_backend
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            # 1x1/s projection shortcut — also a fused-suite shape
            downsample = nn.ConvBNReLU(
                self.inplanes, planes * block.expansion, 1,
                stride=stride, act=None, backend=backend,
                norm_layer=norm_layer)
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width,
                        norm_layer=norm_layer, conv_backend=backend)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width,
                                norm_layer=norm_layer,
                                conv_backend=backend))
        return nn.Sequential(*layers)

    def _stem_conv(self, x):
        """The 7x7/s2 stem conv, computed via space-to-depth when the
        shapes allow: a 3-channel 7x7 conv starves the 128-lane MXU
        (measured v5e: 79 TFLOPS naive vs 413 with s2d). Mathematically
        exact — the input is repacked [B,3,2h,2w] -> [B,12,h+3,w+3] and
        the SAME weights reshaped to an equivalent 4x4/s1 kernel."""
        from paddle_tpu.ops.dispatch import apply, as_tensor

        x = as_tensor(x)
        B, C, H, W = x.shape
        w = self.conv1.weight
        if (C != 3 or H % 2 or W % 2
                or tuple(w.shape[2:]) != (7, 7)
                or tuple(self.conv1._stride) != (2, 2)
                or self.conv1._padding != 3
                or self.conv1.bias is not None):
            # only the canonical 7x7/s2/p3 no-bias stem repacks exactly;
            # anything else (e.g. a CIFAR-style 3x3 stem swap, or the
            # BN-folded stem with its fused bias) runs the plain conv
            return self.conv1(x)

        def fn(a, wt):
            import jax

            b = a.shape[0]
            xp = jax.numpy.pad(a, ((0, 0), (0, 0), (3, 3), (3, 3)))
            h2, w2 = xp.shape[2] // 2, xp.shape[3] // 2
            z = xp.reshape(b, 3, h2, 2, w2, 2)
            z = z.transpose(0, 1, 3, 5, 2, 4).reshape(b, 12, h2, w2)
            w8 = jax.numpy.pad(wt, ((0, 0), (0, 0), (0, 1), (0, 1)))
            wp = w8.reshape(-1, 3, 4, 2, 4, 2).transpose(0, 1, 3, 5, 2, 4) \
                .reshape(-1, 12, 4, 4)
            z = jax.numpy.transpose(z, (0, 2, 3, 1))
            wp = jax.numpy.transpose(wp, (2, 3, 1, 0))
            out = jax.lax.conv_general_dilated(
                z, wp, (1, 1), "VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC")).astype(a.dtype)
            return jax.numpy.transpose(out, (0, 3, 1, 2))

        return apply("resnet_stem_s2d", fn, x, w)

    def forward(self, x):
        x = self.relu(self.bn1(self._stem_conv(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from paddle_tpu.ops import manipulation as mp

            x = mp.flatten(x, 1)
            x = self.fc(x)
        return x


def _resnet(block, depth, **kwargs):
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, **kwargs)
