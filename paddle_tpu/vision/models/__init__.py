from .lenet import LeNet
from .mobilenetv2 import MobileNetV2, mobilenet_v2
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152
from .vgg import VGG, AlexNet, alexnet, vgg11, vgg13, vgg16, vgg19
from .yolo import PPYOLOELite, ppyoloe_lite, yolo_loss, yolo_postprocess

__all__ = ["LeNet", "ResNet", "resnet18", "resnet34", "resnet50",
           "resnet101", "resnet152", "MobileNetV2", "mobilenet_v2",
           "VGG", "vgg11", "vgg13", "vgg16", "vgg19", "AlexNet",
           "alexnet", "PPYOLOELite", "ppyoloe_lite", "yolo_loss",
           "yolo_postprocess"]
