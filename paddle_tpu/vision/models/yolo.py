"""PP-YOLOE-style anchor-free detector — the functional-parity config
for BASELINE.md row 5 ("PP-YOLOE (conv + NMS custom-op path)").

Reference lineage: PaddleDetection's PP-YOLOE (CSPRepResNet backbone,
PAN neck, ET-head) built on the reference framework's conv kernels +
multiclass_nms op. This is a compact TPU-native expression of the same
architecture family — CSP-style conv backbone, top-down FPN neck,
decoupled anchor-free head with center-based assignment — NOT a weight
-compatible port. The full pipeline exercises the detection op tier:
convs on the MXU, varifocal-style cls loss + L1/IoU box losses under
jit.TrainStep, and vision.ops.multiclass_nms postprocessing.

Scale: `ppyoloe_lite()` is deliberately small (train-smoke scale);
width/depth multipliers grow it.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

__all__ = ["PPYOLOELite", "ppyoloe_lite", "yolo_loss", "yolo_postprocess"]


def _conv_bn_act(cin, cout, k=3, s=1):
    return nn.Sequential(
        nn.Conv2D(cin, cout, k, stride=s, padding=k // 2, bias_attr=False),
        nn.BatchNorm2D(cout), nn.Silu())


class CSPBlock(nn.Layer):
    """CSP split-transform-merge (CSPRepResNet family, lite)."""

    def __init__(self, ch, n=1):
        super().__init__()
        half = ch // 2
        self.left = _conv_bn_act(ch, half, 1)
        self.right = nn.Sequential(
            _conv_bn_act(ch, half, 1),
            *[_conv_bn_act(half, half, 3) for _ in range(n)])
        self.fuse = _conv_bn_act(half * 2, ch, 1)

    def forward(self, x):
        import paddle_tpu as paddle

        return self.fuse(paddle.concat([self.left(x), self.right(x)],
                                       axis=1))


class PPYOLOELite(nn.Layer):
    """3-level backbone + top-down neck + decoupled anchor-free head.
    forward(images [B,3,H,W]) -> (cls_logits [B,A,C], boxes [B,A,4],
    anchor_points [A,2], stride_per_anchor [A]) with A = sum of level
    grid cells; boxes are absolute xyxy in input pixels."""

    STRIDES = (8, 16, 32)

    def __init__(self, num_classes=4, width=16):
        super().__init__()
        self.num_classes = num_classes
        w = width
        self.stem = _conv_bn_act(3, w, 3, s=2)          # /2
        self.c2 = nn.Sequential(_conv_bn_act(w, w * 2, 3, s=2),
                                CSPBlock(w * 2))        # /4
        self.c3 = nn.Sequential(_conv_bn_act(w * 2, w * 4, 3, s=2),
                                CSPBlock(w * 4))        # /8
        self.c4 = nn.Sequential(_conv_bn_act(w * 4, w * 8, 3, s=2),
                                CSPBlock(w * 8))        # /16
        self.c5 = nn.Sequential(_conv_bn_act(w * 8, w * 8, 3, s=2),
                                CSPBlock(w * 8))        # /32
        # top-down neck (PAN-lite: upsample + 1x1-reduce + fuse)
        self.lat5 = _conv_bn_act(w * 8, w * 4, 1)
        self.lat4 = _conv_bn_act(w * 8, w * 4, 1)
        self.lat3 = _conv_bn_act(w * 4, w * 4, 1)
        self.fuse4 = CSPBlock(w * 4)
        self.fuse3 = CSPBlock(w * 4)
        self.up = nn.Upsample(scale_factor=2, mode="nearest")
        # decoupled head, shared across levels (ET-head style)
        hc = w * 4
        self.cls_head = nn.Sequential(_conv_bn_act(hc, hc, 3),
                                      nn.Conv2D(hc, num_classes, 1))
        self.reg_head = nn.Sequential(_conv_bn_act(hc, hc, 3),
                                      nn.Conv2D(hc, 4, 1))

    def _grid(self, h, w_, stride):
        """Anchor centers + per-anchor stride for one level; cached per
        feature shape (they depend only on geometry, not on inputs).
        Values made during a jit trace are NOT cached — they would be
        trace-scoped constants that escape as stale tracers."""
        import jax

        import paddle_tpu as paddle

        cache = getattr(self, "_grid_cache", None)
        if cache is None:
            object.__setattr__(self, "_grid_cache", {})
            cache = self._grid_cache
        key = (h, w_, stride)
        if key not in cache:
            ys, xs = np.meshgrid(np.arange(h), np.arange(w_),
                                 indexing="ij")
            pts = paddle.to_tensor(
                ((np.stack([xs, ys], -1).reshape(-1, 2) + 0.5) * stride)
                .astype(np.float32))
            strides = paddle.to_tensor(
                np.full((h * w_,), float(stride), np.float32))
            # empirically, jnp constant creation under this jax
            # version's jit trace yields DynamicJaxprTracers — caching
            # one escapes the trace (UnexpectedTracerError on reuse)
            if isinstance(pts._array, jax.core.Tracer):
                return pts, strides  # trace-scoped: don't cache
            cache[key] = (pts, strides)
        return cache[key]

    def forward(self, x):
        import paddle_tpu as paddle

        p3 = self.c3(self.c2(self.stem(x)))
        p4 = self.c4(p3)
        p5 = self.c5(p4)
        f5 = self.lat5(p5)
        f4 = self.fuse4(self.lat4(p4) + self.up(f5))
        f3 = self.fuse3(self.lat3(p3) + self.up(f4))

        cls_all, box_all, pts_all, str_all = [], [], [], []
        for feat, stride in zip((f3, f4, f5), self.STRIDES):
            cls = self.cls_head(feat)   # [B,C,h,w]
            reg = self.reg_head(feat)   # [B,4,h,w] = l,t,r,b distances
            B, C, h, w_ = cls.shape
            cls = cls.reshape([B, C, h * w_]).transpose([0, 2, 1])
            reg = reg.reshape([B, 4, h * w_]).transpose([0, 2, 1])
            pts, lvl_strides = self._grid(h, w_, stride)
            # distances (>0 via softplus) -> absolute xyxy
            d = F.softplus(reg) * float(stride)
            x1 = pts[:, 0].unsqueeze(0) - d[:, :, 0]
            y1 = pts[:, 1].unsqueeze(0) - d[:, :, 1]
            x2 = pts[:, 0].unsqueeze(0) + d[:, :, 2]
            y2 = pts[:, 1].unsqueeze(0) + d[:, :, 3]
            box = paddle.stack([x1, y1, x2, y2], axis=-1)
            cls_all.append(cls)
            box_all.append(box)
            pts_all.append(pts)
            str_all.append(lvl_strides)
        return (paddle.concat(cls_all, axis=1),
                paddle.concat(box_all, axis=1),
                paddle.concat(pts_all, axis=0),
                paddle.concat(str_all, axis=0))


def yolo_loss(outputs, targets):
    """Anchor-free detection loss with center-based assignment (the
    compact stand-in for PP-YOLOE's TAL/varifocal): an anchor point is
    positive for the first gt box containing it; positives learn
    class scores (BCE, varifocal-style weighting by IoU-free target=1)
    and L1 box offsets; negatives push scores to 0.

    targets: (gt_boxes [B,G,4] xyxy with -1 rows = padding,
              gt_labels [B,G])."""
    import paddle_tpu as paddle

    cls_logits, boxes, pts, strides = outputs
    gt_boxes, gt_labels = targets
    B, A, C = cls_logits.shape
    G = gt_boxes.shape[1]

    px = pts[:, 0].unsqueeze(0).unsqueeze(-1)   # [1,A,1]
    py = pts[:, 1].unsqueeze(0).unsqueeze(-1)
    gx1 = gt_boxes[:, :, 0].unsqueeze(1)        # [B,1,G]
    gy1 = gt_boxes[:, :, 1].unsqueeze(1)
    gx2 = gt_boxes[:, :, 2].unsqueeze(1)
    gy2 = gt_boxes[:, :, 3].unsqueeze(1)
    valid = (gt_boxes[:, :, 2] > gt_boxes[:, :, 0]).unsqueeze(1)  # [B,1,G]
    inside = ((px >= gx1) & (px <= gx2) & (py >= gy1) & (py <= gy2)
              & valid)                          # [B,A,G]
    # first containing gt per anchor
    assigned = inside.cast("float32").argmax(axis=-1)        # [B,A]
    is_pos = inside.any(axis=-1)                             # [B,A]

    one_hot_g = F.one_hot(assigned, G)                       # [B,A,G]
    tgt_box = paddle.einsum("bag,bgk->bak",
                            one_hot_g.cast("float32"), gt_boxes)
    tgt_lab = (one_hot_g.cast("float32") *
               gt_labels.cast("float32").unsqueeze(1)).sum(axis=-1)

    cls_target = (F.one_hot(tgt_lab.cast("int64"), C).cast("float32") *
                  is_pos.cast("float32").unsqueeze(-1))
    cls_loss = F.binary_cross_entropy_with_logits(
        cls_logits, cls_target, reduction="mean")
    posf = is_pos.cast("float32").unsqueeze(-1)
    denom = posf.sum() + 1.0
    # L1 in units of the anchor's stride — scale-invariant across levels
    per_anchor_scale = strides.unsqueeze(0).unsqueeze(-1)  # [1,A,1]
    box_loss = (paddle.abs(boxes - tgt_box) / per_anchor_scale *
                posf).sum() / (denom * 4.0)
    return cls_loss + box_loss


def yolo_postprocess(outputs, score_threshold=0.3, nms_threshold=0.5,
                    keep_top_k=50):
    """Decode one batch to detections via the multiclass NMS op tier.
    Returns a list (per image) of [K,6] arrays (label, score, xyxy)."""
    from paddle_tpu.vision import ops

    cls_logits, boxes, _, _ = outputs
    probs = F.sigmoid(cls_logits)
    results = []
    for b in range(cls_logits.shape[0]):
        out, k = ops.multiclass_nms(
            boxes[b], probs[b].transpose([1, 0]),
            score_threshold=score_threshold,
            nms_threshold=nms_threshold, keep_top_k=keep_top_k)
        results.append(np.asarray(out)[:int(k)])
    return results


def ppyoloe_lite(num_classes=4, width=16):
    return PPYOLOELite(num_classes=num_classes, width=width)
