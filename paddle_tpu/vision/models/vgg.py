"""VGG + AlexNet — analogs of python/paddle/vision/models/vgg.py and
alexnet.py (classic conv stacks; the MXU eats these whole)."""
from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19", "AlexNet",
           "alexnet"]

_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


def _vgg_features(cfg, batch_norm):
    steps, cin = [], 3
    for v in cfg:
        if v == "M":
            steps.append(nn.MaxPool2D(kernel_size=2, stride=2))
            continue
        steps.append(nn.Conv2D(cin, v, 3, padding=1))
        if batch_norm:
            steps.append(nn.BatchNorm2D(v))
        steps.append(nn.ReLU())
        cin = v
    return nn.Sequential(*steps)


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(x.reshape([x.shape[0], -1]))
        return x


def _vgg(cfg, batch_norm, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("no pretrained weights in this build")
    return VGG(_vgg_features(_VGG_CFGS[cfg], batch_norm), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kw):
    return _vgg("A", batch_norm, pretrained, **kw)


def vgg13(pretrained=False, batch_norm=False, **kw):
    return _vgg("B", batch_norm, pretrained, **kw)


def vgg16(pretrained=False, batch_norm=False, **kw):
    return _vgg("D", batch_norm, pretrained, **kw)


def vgg19(pretrained=False, batch_norm=False, **kw):
    return _vgg("E", batch_norm, pretrained, **kw)


class AlexNet(nn.Layer):
    """alexnet.py analog (the 2012 stack, modern single-GPU layout)."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(kernel_size=3, stride=2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(kernel_size=3, stride=2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(kernel_size=3, stride=2),
        )
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x.reshape([x.shape[0], -1]))
        return x


def alexnet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("no pretrained weights in this build")
    return AlexNet(**kwargs)
