"""paddle.onnx analog (python/paddle/onnx/ is a thin paddle2onnx
wrapper). This build's native serialized format is StableHLO
(paddle.jit.save -> portable, versioned, loadable by paddle.jit.load
into an executable predictor); ONNX export is provided only when the
`onnx` package is installed, mirroring the reference's soft dependency
on paddle2onnx.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export `layer` to ONNX at `path`.onnx. Requires the optional
    `onnx` package; without it, use paddle.jit.save (StableHLO) — the
    portable format this framework serves natively."""
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "paddle.onnx.export needs the optional 'onnx' package, which "
            "is not installed in this environment. The TPU-native "
            "portable format is StableHLO: paddle.jit.save(layer, path) "
            "then paddle.jit.load(path) returns an executable predictor "
            "(no original Python source needed)") from e
    raise NotImplementedError(
        "ONNX op-graph emission is not implemented; export via "
        "paddle.jit.save (StableHLO) instead")
