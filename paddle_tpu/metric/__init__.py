"""paddle.metric analog (python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.tensor import Tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label):
        pred_np = np.asarray(pred._array if isinstance(pred, Tensor) else pred)
        label_np = np.asarray(label._array if isinstance(label, Tensor) else label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        maxk = max(self.topk)
        top_idx = np.argsort(-pred_np, axis=-1)[..., :maxk]
        correct = top_idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct):
        c = np.asarray(correct._array if isinstance(correct, Tensor) else correct)
        accs = []
        for i, k in enumerate(self.topk):
            num = float(c[..., :k].sum())
            self.total[i] += num
            self.count[i] += c.shape[0] if c.ndim > 1 else len(c)
            accs.append(self.total[i] / max(self.count[i], 1))
        return accs if len(accs) > 1 else accs[0]

    def accumulate(self):
        out = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return out if len(out) > 1 else out[0]

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds._array if isinstance(preds, Tensor) else preds).round()
        l = np.asarray(labels._array if isinstance(labels, Tensor) else labels)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds._array if isinstance(preds, Tensor) else preds).round()
        l = np.asarray(labels._array if isinstance(labels, Tensor) else labels)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds._array if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._array if isinstance(labels, Tensor) else labels).reshape(-1)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(int), self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate over thresholds from high to low
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    m = Accuracy(topk=(k,))
    correct = m.compute(input, label)
    return Tensor(np.asarray(m.update(correct)))
