"""paddle.fft analog (python/paddle/fft.py): FFT family over jnp.fft,
dispatched through the op layer so transforms are differentiable on the
tape and fuse under jit (TPU lowers FFTs natively)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.dispatch import apply, as_tensor

__all__ = ["fft", "ifft", "rfft", "irfft", "fft2", "ifft2", "rfft2",
           "irfft2", "fftn", "ifftn", "fftshift", "ifftshift",
           "fftfreq", "rfftfreq", "hfft", "ihfft"]


def _mk(name, jfn, takes_n=True):
    if takes_n:
        def op(x, n=None, axis=-1, norm="backward", name=None):
            return apply(name, lambda a: jfn(a, n=n, axis=axis, norm=norm),
                         as_tensor(x))
    else:
        def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
            return apply(name, lambda a: jfn(a, s=s, axes=axes, norm=norm),
                         as_tensor(x))
    op.__name__ = name
    return op


fft = _mk("fft", jnp.fft.fft)
ifft = _mk("ifft", jnp.fft.ifft)
rfft = _mk("rfft", jnp.fft.rfft)
irfft = _mk("irfft", jnp.fft.irfft)
hfft = _mk("hfft", jnp.fft.hfft)
ihfft = _mk("ihfft", jnp.fft.ihfft)
fft2 = _mk("fft2", jnp.fft.fft2, takes_n=False)
ifft2 = _mk("ifft2", jnp.fft.ifft2, takes_n=False)
rfft2 = _mk("rfft2", jnp.fft.rfft2, takes_n=False)
irfft2 = _mk("irfft2", jnp.fft.irfft2, takes_n=False)


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return apply("fftn", lambda a: jnp.fft.fftn(a, s=s, axes=axes,
                                                norm=norm), as_tensor(x))


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return apply("ifftn", lambda a: jnp.fft.ifftn(a, s=s, axes=axes,
                                                  norm=norm), as_tensor(x))


def fftshift(x, axes=None, name=None):
    return apply("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes),
                 as_tensor(x))


def ifftshift(x, axes=None, name=None):
    return apply("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes),
                 as_tensor(x))


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(n, d=d)
    return Tensor._wrap(out.astype(dtype) if dtype is not None else out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(n, d=d)
    return Tensor._wrap(out.astype(dtype) if dtype is not None else out)
