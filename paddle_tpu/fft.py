"""paddle.fft analog (python/paddle/fft.py): FFT family over jnp.fft,
dispatched through the op layer so transforms are differentiable on the
tape and fuse under jit (TPU lowers FFTs natively)."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.dispatch import apply, as_tensor

__all__ = ["fft", "ifft", "rfft", "irfft", "fft2", "ifft2", "rfft2",
           "irfft2", "fftn", "ifftn", "rfftn", "irfftn", "fftshift",
           "ifftshift", "fftfreq", "rfftfreq", "hfft", "ihfft",
           "hfft2", "ihfft2", "hfftn", "ihfftn"]


def _backend_fft_ok() -> bool:
    """Whether the default backend lowers FFT + holds complex buffers —
    exactly device.supports_complex() (production CPU/GPU/TPU XLA: yes;
    the experimental axon tunnel: no, and it cannot be probed at runtime
    because a failed op wedges its process state)."""
    from paddle_tpu.core.device import supports_complex

    return supports_complex()


def _dispatch(opname, call, x):
    """Native FFT lowering first; on an FFT-less backend, eager calls
    hop to the CPU backend (ops.dispatch.apply_with_cpu_fallback)."""
    from paddle_tpu.ops.dispatch import apply_with_cpu_fallback

    return apply_with_cpu_fallback(apply, opname, call, as_tensor(x),
                                   _backend_fft_ok,
                                   complex_stays_on_cpu=True)


def _mk(opname, jfn, takes_n=True):
    if takes_n:
        def op(x, n=None, axis=-1, norm="backward", name=None):
            return _dispatch(opname,
                             lambda a: jfn(a, n=n, axis=axis, norm=norm), x)
    else:
        def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
            return _dispatch(opname,
                             lambda a: jfn(a, s=s, axes=axes, norm=norm), x)
    op.__name__ = opname
    return op


fft = _mk("fft", jnp.fft.fft)
ifft = _mk("ifft", jnp.fft.ifft)
rfft = _mk("rfft", jnp.fft.rfft)
irfft = _mk("irfft", jnp.fft.irfft)
hfft = _mk("hfft", jnp.fft.hfft)
ihfft = _mk("ihfft", jnp.fft.ihfft)
fft2 = _mk("fft2", jnp.fft.fft2, takes_n=False)
ifft2 = _mk("ifft2", jnp.fft.ifft2, takes_n=False)
rfft2 = _mk("rfft2", jnp.fft.rfft2, takes_n=False)
irfft2 = _mk("irfft2", jnp.fft.irfft2, takes_n=False)


def _mkn(opname, jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return _dispatch(opname,
                         lambda a: jfn(a, s=s, axes=axes, norm=norm), x)
    op.__name__ = opname
    return op


fftn = _mkn("fftn", jnp.fft.fftn)
ifftn = _mkn("ifftn", jnp.fft.ifftn)
rfftn = _mkn("rfftn", jnp.fft.rfftn)
irfftn = _mkn("irfftn", jnp.fft.irfftn)


def _hermitian_nd(opname, axis_fn):
    """jnp.fft has no hfft2/hfftn; compose from the 1-d hermitian
    transform over the last axis + complex FFTs over the rest, matching
    scipy/paddle semantics. Order matters: hfft* runs the complex FFTs
    first and the C2R hfft over the last axis LAST (real output);
    ihfft* runs the R2C ihfft over the last axis FIRST."""
    def op(x, s=None, axes=None, norm="backward", name=None):
        def run(a):
            if axes is not None:
                ax = list(axes)
            elif "2" in opname:
                ax = [-2, -1]
            elif s is not None:
                ax = list(range(-len(s), 0))  # last len(s) axes
            else:
                ax = list(range(a.ndim))
            *rest, last = ax
            nlast = None if s is None else s[-1]

            def complex_ffts(out):
                for i, r in enumerate(rest):
                    nr = None if s is None else s[i]
                    jfn = jnp.fft.fft if opname.startswith("h") else \
                        jnp.fft.ifft
                    out = jfn(out, n=nr, axis=r, norm=norm)
                return out

            if opname.startswith("h"):  # C2R last
                return axis_fn(complex_ffts(a), n=nlast, axis=last,
                               norm=norm)
            # R2C first
            return complex_ffts(axis_fn(a, n=nlast, axis=last, norm=norm))
        return _dispatch(opname, run, x)
    op.__name__ = opname
    return op


hfft2 = _hermitian_nd("hfft2", jnp.fft.hfft)
ihfft2 = _hermitian_nd("ihfft2", jnp.fft.ihfft)
hfftn = _hermitian_nd("hfftn", jnp.fft.hfft)
ihfftn = _hermitian_nd("ihfftn", jnp.fft.ihfft)


def fftshift(x, axes=None, name=None):
    return apply("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes),
                 as_tensor(x))


def ifftshift(x, axes=None, name=None):
    return apply("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes),
                 as_tensor(x))


def fftfreq(n, d=1.0, dtype=None, name=None):
    from paddle_tpu.core import dtype as dtypes

    out = jnp.fft.fftfreq(n, d=d)
    return Tensor._wrap(out.astype(dtypes.to_jax(dtype))
                        if dtype is not None else out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from paddle_tpu.core import dtype as dtypes

    out = jnp.fft.rfftfreq(n, d=d)
    return Tensor._wrap(out.astype(dtypes.to_jax(dtype))
                        if dtype is not None else out)
