"""Conv layers — analog of python/paddle/nn/layer/conv.py. Weight layout
OIHW (paddle); convs lower to lax.conv_general_dilated on the MXU."""
from __future__ import annotations

import numpy as np

from paddle_tpu.ops import nn_ops

from .layer import Layer


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nsp,
                 stride=1, padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _pair(kernel_size, nsp)
        self._stride = _pair(stride, nsp)
        self._padding = padding
        self._dilation = _pair(dilation, nsp)
        self._groups = groups
        self._data_format = data_format
        fan_in = in_channels // groups * int(np.prod(self._kernel_size))
        w_shape = [out_channels, in_channels // groups] + list(self._kernel_size)
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in))
        bound = 1.0 / np.sqrt(fan_in)
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound))


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format)

    def forward(self, x):
        return nn_ops.conv2d(x, self.weight, self.bias, self._stride,
                             self._padding, self._dilation, self._groups,
                             self._data_format)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format)

    def forward(self, x):
        return nn_ops.conv1d(x, self.weight, self.bias, self._stride,
                             self._padding, self._dilation, self._groups)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format)

    def forward(self, x):
        return nn_ops.conv3d(x, self.weight, self.bias, self._stride,
                             self._padding, self._dilation, self._groups)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self._stride = _pair(stride)
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = _pair(dilation)
        self._groups = groups
        ks = _pair(kernel_size)
        fan_in = in_channels * int(np.prod(ks))
        # paddle transpose-conv weight layout: [in, out//groups, kh, kw]
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups] + list(ks),
            attr=weight_attr, default_initializer=I.KaimingUniform(fan_in=fan_in))
        self.bias = self.create_parameter([out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return nn_ops.conv2d_transpose(
            x, self.weight, self.bias, self._stride, self._padding,
            self._output_padding, self._dilation, self._groups)
