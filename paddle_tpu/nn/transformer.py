"""Transformer layers — analog of python/paddle/nn/layer/transformer.py
(MultiHeadAttention, TransformerEncoderLayer, ...). Attention dispatches
to ops.nn_ops.scaled_dot_product_attention (XLA path) or the Pallas flash
kernel for long sequences; layouts follow paddle: [batch, seq, d_model],
per-head [batch, seq, heads, head_dim].
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.ops import activation as act
from paddle_tpu.ops import manipulation as mp
from paddle_tpu.ops import nn_ops

from .common import Dropout, Linear
from .container import LayerList
from .layer import Layer
from .norm import LayerNorm


class MultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        B, S = query.shape[0], query.shape[1]
        H, D = self.num_heads, self.head_dim
        q = mp.reshape(self.q_proj(query), [B, S, H, D])
        k = mp.reshape(self.k_proj(key), [B, key.shape[1], H, D])
        v = mp.reshape(self.v_proj(value), [B, value.shape[1], H, D])
        if cache is not None:
            k = mp.concat([cache[0], k], axis=1)
            v = mp.concat([cache[1], v], axis=1)
        out = nn_ops.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            training=self.training)
        out = mp.reshape(out, [B, S, H * D])
        out = self.out_proj(out)
        if cache is not None:
            return out, (k, v)
        return out

    def gen_cache(self, key, value=None, type=None):
        from paddle_tpu.ops import creation

        B = key.shape[0]
        k0 = creation.zeros([B, 0, self.num_heads, self.head_dim])
        v0 = creation.zeros([B, 0, self.num_heads, self.head_dim])
        return (k0, v0)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = getattr(act, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.self_attn(src, src, src, attn_mask=src_mask)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] +
                                [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = getattr(act, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, tgt, tgt, attn_mask=tgt_mask)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, attn_mask=memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([decoder_layer] +
                                [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None):
        out = tgt
        for layer in self.layers:
            out = layer(out, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import jax.numpy as jnp

        from paddle_tpu.core.tensor import Tensor

        mask = jnp.where(
            jnp.tril(jnp.ones((length, length), bool)), 0.0, -jnp.inf
        ).astype(jnp.float32)
        return Tensor._wrap(mask)
