"""Layer base class — analog of python/paddle/fluid/dygraph/layers.py
(paddle.nn.Layer): parameter/sublayer registries, hooks, state_dict,
train/eval mode. Parameters are eager Tensors (PJRT buffers on TPU); the
functional views used by jit.TrainStep read them as a pytree.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

from paddle_tpu.core import dtype as dtypes
from paddle_tpu.core.tensor import Parameter, Tensor


class ParamAttr:
    """Analog of paddle.ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable

    @staticmethod
    def _to_attr(attr):
        if attr is None or attr is True:
            return ParamAttr()
        if attr is False:
            return None
        if isinstance(attr, ParamAttr):
            return attr
        # an Initializer instance
        return ParamAttr(initializer=attr)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        self.training = True
        self._dtype = dtypes.canonical_name(dtype)
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # -- attribute routing -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            for d in (layers, buffers):
                if d is not None and name in d:
                    del d[name]
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None and name in d:
                    del d[name]
            layers[name] = value
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            else:
                params[name] = value
        elif layers is not None and name in layers:
            layers[name] = value
        elif buffers is not None and name in buffers:
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for registry in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__} has no attribute {name!r}")

    def __delattr__(self, name):
        for registry in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(registry)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- parameter management ----------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """Analog of Layer.create_parameter (dygraph/layers.py)."""
        from paddle_tpu.nn import initializer as I

        attr = ParamAttr._to_attr(attr)
        if attr is None:
            return None
        dtype = dtype or self._dtype
        init = attr.initializer or default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        data = init(shape, dtype)
        p = Parameter(data, dtype=dtype, name=attr.name or "",
                      trainable=attr.trainable)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        if tensor is not None:
            tensor.persistable = persistable
        self._buffers[name] = tensor
        return tensor

    # -- iteration ---------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(sub_prefix, True):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix, True)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items() if l is not None)

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for l in self._sub_layers.values():
            if l is not None:
                out.extend(l.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield from l.named_sublayers(p, include_self=True)

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- mode --------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # -- state dict ----------------------------------------------------------
    def state_dict(self, include_sublayers=True, structured_name_prefix=""):
        out = OrderedDict()
        for n, p in self.named_parameters(prefix=structured_name_prefix):
            out[n] = p
        for n, b in self.named_buffers(prefix=structured_name_prefix):
            if b.persistable:
                out[n] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                v = state_dict[name]
                arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                t.set_value(arr)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- dtype / device movement -------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            jd = dtypes.to_jax(dtype)
            for p in self.parameters():
                if dtypes.is_floating(p.dtype):
                    p._array = p._array.astype(jd)
            for b in self.buffers():
                if dtypes.is_floating(b.dtype):
                    b._array = b._array.astype(jd)
            for l in self.sublayers(include_self=True):
                l._dtype = dtypes.canonical_name(dtype)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks, hook)
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks, hook)
        return handle

    # -- call --------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            rep = repr(layer).split("\n")
            rep = [rep[0]] + ["  " + l for l in rep[1:]]
            lines.append(f"  ({name}): " + "\n".join(rep))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def full_name(self):
        return self._name_scope

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


class _HookHandle:
    _next_id = 0

    def __init__(self, registry, hook):
        _HookHandle._next_id += 1
        self.hook_id = _HookHandle._next_id
        self._registry = registry
        registry[self.hook_id] = hook

    def remove(self):
        self._registry.pop(self.hook_id, None)
