"""Normalization layers — analog of python/paddle/nn/layer/norm.py."""
from __future__ import annotations

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import nn_ops

from .layer import Layer


class BatchNorm2D(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        import jax.numpy as jnp

        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        training = self.training and not self._use_global_stats
        return nn_ops.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm1D(BatchNorm2D):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL", name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW")
        self._data_format = "NCHW"  # reduce over all but axis 1 regardless


class BatchNorm3D(BatchNorm2D):
    pass


BatchNorm = BatchNorm2D


class SyncBatchNorm(BatchNorm2D):
    """Under SPMD data parallel the batch statistics are computed over the
    global (sharded) batch automatically when the step is compiled with a
    'dp'-sharded mesh — cross-replica reduction is inserted by XLA. In
    eager single-device mode it equals BatchNorm. Analog of
    paddle.nn.SyncBatchNorm (nn/layer/norm.py)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return nn_ops.layer_norm(x, self._normalized_shape, self.weight,
                                 self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """TPU-era addition (no v2.4 analog); used by the GPT flagship."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=I.Constant(1.0))

    def forward(self, x):
        return nn_ops.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self._num_groups = num_groups
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return nn_ops.group_norm(x, self._num_groups, self.weight, self.bias,
                                 self._epsilon)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self._epsilon = epsilon
        self.scale = self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return nn_ops.instance_norm(x, self.scale, self.bias, self._epsilon)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW"):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        return nn_ops.local_response_norm(
            x, self.size, alpha=self.alpha, beta=self.beta, k=self.k)


class SpectralNorm(Layer):
    """Spectral normalization (python/paddle/nn/layer/norm.py
    SpectralNorm; phi spectral_norm kernel): returns weight / sigma_max,
    sigma estimated by power iteration. The u/v vectors persist as
    buffers and advance power_iters steps per forward (train mode),
    matching the reference's in-forward iteration."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        import jax
        import jax.numpy as jnp
        import numpy as np

        from paddle_tpu.core import random as prandom

        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = int(weight_shape[dim])
        w = int(np.prod(weight_shape)) // h
        k1, k2 = jax.random.split(prandom.next_key())
        u = jax.random.normal(k1, (h,), jnp.float32)
        v = jax.random.normal(k2, (w,), jnp.float32)
        self.register_buffer("weight_u", Tensor(u / jnp.linalg.norm(u)))
        self.register_buffer("weight_v", Tensor(v / jnp.linalg.norm(v)))

    def forward(self, weight):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.ops.dispatch import apply, as_tensor

        dim, iters, eps = self.dim, self.power_iters, self.eps
        # the reference's spectral_norm op runs power_iters EVERY
        # forward (train and eval) — u/v from init are random, so
        # skipping iteration would divide by a meaningless sigma
        do_iter = True

        def fn(w, u, v):
            perm = (dim,) + tuple(i for i in range(w.ndim) if i != dim)
            m = jnp.transpose(w, perm).reshape(w.shape[dim], -1)
            uu, vv = u, v
            if do_iter:
                for _ in range(iters):
                    vv = m.T @ uu
                    vv = vv / (jnp.linalg.norm(vv) + eps)
                    uu = m @ vv
                    uu = uu / (jnp.linalg.norm(uu) + eps)
            # power-iteration state is an estimate, not a differentiable
            # path (reference stops gradients through u/v)
            uu = jax.lax.stop_gradient(uu)
            vv = jax.lax.stop_gradient(vv)
            sigma = uu @ (m @ vv)
            return w / sigma, uu, vv

        out, u2, v2 = apply("spectral_norm", fn, as_tensor(weight),
                            self.weight_u, self.weight_v)
        # persist the advanced power-iteration state. Inside a compiled
        # train step (bound_state scope) the arrays are tracers, but
        # make_forward_loss captures buffer writes and threads them
        # through the step's outputs, so writing is both safe and
        # required for sigma to converge across steps. Outside any
        # bound_state scope a tracer write would leak into the eager
        # world (e.g. a bare jax.jit over forward) — skip it there.
        # Only train mode advances the stored state (eval iterates from
        # it but leaves it untouched, so eval is idempotent).
        from paddle_tpu.jit.api import buffer_writes_captured
        if self.training and (buffer_writes_captured()
                              or not isinstance(u2._array, jax.core.Tracer)):
            self.weight_u._array = u2._array
            self.weight_v._array = v2._array
        return out
