"""Normalization layers — analog of python/paddle/nn/layer/norm.py."""
from __future__ import annotations

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops import nn_ops

from .layer import Layer


class BatchNorm2D(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        import jax.numpy as jnp

        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, x):
        training = self.training and not self._use_global_stats
        return nn_ops.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm1D(BatchNorm2D):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL", name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW")
        self._data_format = "NCHW"  # reduce over all but axis 1 regardless


class BatchNorm3D(BatchNorm2D):
    pass


BatchNorm = BatchNorm2D


class SyncBatchNorm(BatchNorm2D):
    """Under SPMD data parallel the batch statistics are computed over the
    global (sharded) batch automatically when the step is compiled with a
    'dp'-sharded mesh — cross-replica reduction is inserted by XLA. In
    eager single-device mode it equals BatchNorm. Analog of
    paddle.nn.SyncBatchNorm (nn/layer/norm.py)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return nn_ops.layer_norm(x, self._normalized_shape, self.weight,
                                 self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """TPU-era addition (no v2.4 analog); used by the GPT flagship."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=I.Constant(1.0))

    def forward(self, x):
        return nn_ops.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self._num_groups = num_groups
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return nn_ops.group_norm(x, self._num_groups, self.weight, self.bias,
                                 self._epsilon)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self._epsilon = epsilon
        self.scale = self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return nn_ops.instance_norm(x, self.scale, self.bias, self._epsilon)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW"):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.ops.dispatch import apply

        size, alpha, beta, k = self.size, self.alpha, self.beta, self.k

        def fn(a):
            sq = jnp.square(a)
            half = size // 2
            summed = jax.lax.reduce_window(
                sq, 0.0, jax.lax.add, (1, size, 1, 1), (1, 1, 1, 1),
                padding=[(0, 0), (half, size - 1 - half), (0, 0), (0, 0)])
            return a / jnp.power(k + alpha * summed, beta)

        return apply("lrn", fn, x)
