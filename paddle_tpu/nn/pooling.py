"""Pooling layers — analog of python/paddle/nn/layer/pooling.py."""
from __future__ import annotations

from paddle_tpu.ops import nn_ops

from .layer import Layer


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode

    def forward(self, x):
        return nn_ops.max_pool2d(x, self.kernel_size, self.stride,
                                 self.padding, self.ceil_mode)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.exclusive = exclusive

    def forward(self, x):
        return nn_ops.avg_pool2d(x, self.kernel_size, self.stride,
                                 self.padding, count_include_pad=not self.exclusive)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def forward(self, x):
        return nn_ops.max_pool1d(x, self.kernel_size, self.stride, self.padding)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding

    def forward(self, x):
        return nn_ops.avg_pool1d(x, self.kernel_size, self.stride, self.padding)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return nn_ops.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return nn_ops.adaptive_max_pool2d(x, self.output_size)


class MaxPool3D(Layer):
    """python/paddle/nn/layer/pooling.py MaxPool3D; x [B,C,D,H,W]."""

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCDHW", name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = \
            kernel_size, stride, padding
        self.ceil_mode, self.return_mask = ceil_mode, return_mask
        self.data_format = data_format

    def forward(self, x):
        return nn_ops.max_pool3d(x, self.kernel_size, self.stride,
                                 self.padding, self.ceil_mode,
                                 self.return_mask, self.data_format)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None,
                 data_format="NCDHW", name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = \
            kernel_size, stride, padding
        self.exclusive = exclusive
        self.ceil_mode, self.divisor_override = ceil_mode, divisor_override
        self.data_format = data_format

    def forward(self, x):
        return nn_ops.avg_pool3d(x, self.kernel_size, self.stride,
                                 self.padding, self.ceil_mode,
                                 self.exclusive, self.divisor_override,
                                 self.data_format)
