"""Weight initializers — analog of python/paddle/nn/initializer/.

Each initializer is a callable (shape, dtype) -> jax array, drawing from
the global PRNG chain so `paddle_tpu.seed()` makes init deterministic.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import dtype as dtypes
from paddle_tpu.core.random import next_key

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]  # paddle linear weight [in, out]
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    return gains.get(nonlinearity, 1.0)


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(tuple(shape), self.value, dtypes.to_jax(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        d = dtypes.to_jax(dtype)
        return self.mean + self.std * jax.random.normal(next_key(), tuple(shape), d)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        d = dtypes.to_jax(dtype)
        return self.mean + self.std * jax.random.truncated_normal(
            next_key(), -2.0, 2.0, tuple(shape), d
        )


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        d = dtypes.to_jax(dtype)
        return jax.random.uniform(next_key(), tuple(shape), d, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(next_key(), tuple(shape), dtypes.to_jax(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(
            next_key(), tuple(shape), dtypes.to_jax(dtype), -limit, limit
        )


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(next_key(), tuple(shape), dtypes.to_jax(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(
            next_key(), tuple(shape), dtypes.to_jax(dtype), -limit, limit
        )


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        arr = jnp.asarray(np.asarray(self.value), dtypes.to_jax(dtype))
        return arr.reshape(tuple(shape))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        return self.gain * jax.nn.initializers.orthogonal()(
            next_key(), tuple(shape), dtypes.to_jax(dtype)
        )


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        o, i = shape[0], shape[1]
        arr = np.zeros(tuple(shape), np.float32)
        centers = tuple(s // 2 for s in shape[2:])
        for k in range(min(o, i * self.groups)):
            arr[(k, k % i) + centers] = 1.0
        return jnp.asarray(arr, dtypes.to_jax(dtype))
