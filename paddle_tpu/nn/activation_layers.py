"""Activation layers — analog of python/paddle/nn/layer/activation.py."""
from __future__ import annotations

from paddle_tpu.ops import activation as act

from .layer import Layer


def _simple(name, fn):
    class _Act(Layer):
        def __init__(self, name=None):
            super().__init__()

        def forward(self, x):
            return fn(x)

    _Act.__name__ = name
    return _Act


ReLU = _simple("ReLU", act.relu)
ReLU6 = _simple("ReLU6", act.relu6)
Sigmoid = _simple("Sigmoid", act.sigmoid)
Tanh = _simple("Tanh", act.tanh)
Silu = _simple("Silu", act.silu)
Swish = _simple("Swish", act.swish)
Mish = _simple("Mish", act.mish)
Hardswish = _simple("Hardswish", act.hardswish)
Hardsigmoid = _simple("Hardsigmoid", act.hardsigmoid)
Softsign = _simple("Softsign", act.softsign)
Tanhshrink = _simple("Tanhshrink", act.tanhshrink)
LogSigmoid = _simple("LogSigmoid", act.log_sigmoid)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return act.gelu(x, self.approximate)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return act.leaky_relu(x, self.negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return act.elu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return act.selu(x, self.scale, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return act.celu(x, self.alpha)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, name=None):
        super().__init__()
        from paddle_tpu.nn import initializer as I

        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return act.prelu(x, self.weight)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return act.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return act.log_softmax(x, self.axis)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return act.softplus(x, self.beta, self.threshold)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return act.hardtanh(x, self.min, self.max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return act.hardshrink(x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return act.softshrink(x, self.threshold)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return act.maxout(x, self.groups, self.axis)
