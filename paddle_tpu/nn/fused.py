"""Fused layer blocks — the nn tier over the Pallas conv suite
(`ops/pallas/conv.py`) plus inference-time BatchNorm folding.

`ConvBNReLU` is the building block `vision/models/resnet.py` consumes:
a Conv2D + BatchNorm2D (+ optional ReLU) whose EVAL forward can run as
ONE fused Pallas kernel — conv as MXU matmuls with fp32 accumulation,
the BN scale/shift and ReLU applied in-register before the single HBM
write-back — behind the same `auto`/`dense`/`pallas` backend seam as
paged attention (env override `PADDLE_CONV_BACKEND` wins, resolved
ONCE at construction). The dense backend is byte-for-byte today's
`nn_ops.conv2d` + `BatchNorm` + `relu` composition and stays the
exactness foil. TRAINING on a pallas-resolved block runs fused too:
`fused_conv_bn_relu_train` is a `jax.custom_vjp` whose forward fuses
the batch-stat computation into the conv kernel's epilogue and whose
backward runs the fused dInput/dWeight kernels — the block updates
the BN running stats from the returned batch mean/var with exactly
the `nn_ops.batch_norm` momentum rule. Dense-resolved training (and
any geometry the train gate rejects — use_global_stats BN, untileable
walks) keeps the identical pre-suite composition graph. NOTE: the
refactor is graph-compatible, not checkpoint-key-compatible — resnet
block state_dict keys moved from `conv1.weight`/`bn1.*` to
`convbn1.conv.weight`/`convbn1.bn.*` (and `downsample.0.*` to
`downsample.conv.*`); checkpoints saved before the suite landed need
a key rename on load.

`fold_bn_into_conv` / `fuse_conv_bn` are the deploy-time counterpart:
fold the (running-stat) BatchNorm affine into the conv weights/bias so
eval forward skips the BN op entirely — the standard inference
deployment transform, exact up to one float rounding of the folded
weights.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.ops.dispatch import apply_nograd, as_tensor

from .common import Identity
from .conv import Conv2D
from .layer import Layer
from .norm import BatchNorm2D

__all__ = ["ConvBNReLU", "fold_bn_into_conv", "fuse_conv_bn"]


class ConvBNReLU(Layer):
    """Conv2D + BatchNorm2D + optional ReLU with a fused-kernel eval
    path.

    `act` is `"relu"` or None (the bn3 / downsample shape). `backend`
    is `auto`/`dense`/`pallas` (default auto; `PADDLE_CONV_BACKEND`
    wins), resolved once here: unsupported geometries — the 7x7/s2
    stem, grouped/dilated convs, ragged channels — resolve `dense`
    cleanly whatever was asked. On a resolved-`pallas` block the
    fused kernels engage in BOTH modes: eval through the forward-only
    folded-affine kernel, training through the `custom_vjp` batch-stat
    op with fused backward. Everything else (the dense backend, a
    custom norm layer, use_global_stats BN, a geometry either tile
    gate rejects) runs the composition the rest of the framework
    already trains through — `CONV_PATH_STATS` counts the train-mode
    routes separately so a fallback is observable."""

    def __init__(self, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, dilation=1, groups=1,
                 act="relu", backend=None, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        from paddle_tpu.ops.pallas.conv import resolve_conv_backend

        if act not in ("relu", None):
            raise ValueError(f"act must be 'relu' or None, got {act!r}")
        norm_layer = norm_layer or BatchNorm2D
        self.conv = Conv2D(in_channels, out_channels, kernel_size,
                           stride=stride, padding=padding,
                           dilation=dilation, groups=groups,
                           bias_attr=False, data_format=data_format)
        self.bn = norm_layer(out_channels)
        self._act = act
        self._data_format = data_format
        self._folded = False
        self.backend_requested = backend or "auto"
        self.backend = resolve_conv_backend(
            backend, kernel=self.conv._kernel_size,
            stride=self.conv._stride, in_channels=in_channels,
            out_channels=out_channels, dilation=self.conv._dilation,
            groups=groups, padding=padding)
        if not isinstance(self.bn, BatchNorm2D):
            # a custom norm has no (mean, var, gamma, beta) affine to
            # fold into the kernel epilogue — composition only
            self.backend = "dense"

    def extra_repr(self):
        return (f"{self.conv._in_channels}, {self.conv._out_channels}, "
                f"kernel_size={self.conv._kernel_size}, "
                f"stride={self.conv._stride}, act={self._act!r}, "
                f"backend={self.backend}")

    def _compose(self, x):
        """The dense exactness foil: today's conv -> BN -> ReLU
        composition, unchanged (XLA fuses the element-wise tail)."""
        from paddle_tpu.ops.pallas.conv import CONV_PATH_STATS

        CONV_PATH_STATS["dense_train" if self.training
                        else "dense"] += 1
        out = self.conv(x)
        if not self._folded:
            out = self.bn(out)
        if self._act == "relu":
            from paddle_tpu.ops.activation import relu

            out = relu(out)
        return out

    def forward(self, x):
        if self.backend == "pallas" and not self._folded:
            if not self.training and self._geometry_tileable(x):
                return self._forward_fused(x)
            if self.training and self._train_fusible(x):
                return self._forward_fused_train(x)
        return self._compose(x)

    def _geometry_tileable(self, x):
        """The H/W-dependent half of the support gate, checked per
        forward (static resolution cannot see the input size): a
        geometry the 3x3 kernel cannot tile — too many row tiles, a
        slab overrunning the padded input or the VMEM budget — runs
        the dense composition, the same clean fallback as the static
        gate."""
        from paddle_tpu.ops.pallas.conv import conv_geometry_tileable

        hw = x.shape[2:4] if self._data_format == "NCHW" \
            else x.shape[1:3]
        return conv_geometry_tileable(self.conv._kernel_size,
                                      self.conv._stride,
                                      self.conv._padding, in_hw=hw,
                                      in_channels=self.conv._in_channels)

    def _train_fusible(self, x):
        """Training-mode gate on a pallas-resolved block: batch-stat
        BatchNorm only (`use_global_stats` pins running stats — the
        fused train op computes batch stats by construction) and both
        the forward AND backward walks must tile
        (`conv_train_geometry_tileable`). Anything else runs the
        dense composition — a clean fallback counted in
        `CONV_PATH_STATS["dense_train"]`, never a silent
        divergence."""
        from paddle_tpu.ops.pallas.conv import \
            conv_train_geometry_tileable

        if not isinstance(self.bn, BatchNorm2D) or \
                self.bn._use_global_stats:
            return False
        hw = x.shape[2:4] if self._data_format == "NCHW" \
            else x.shape[1:3]
        return conv_train_geometry_tileable(
            self.conv._kernel_size, self.conv._stride,
            self.conv._padding, in_hw=hw,
            in_channels=self.conv._in_channels,
            out_channels=self.conv._out_channels)

    def _forward_fused(self, x):
        """ONE dispatch: BN affine folded to (scale, shift) in fp32,
        layout swapped to the kernels' NHWC, the fused Pallas kernel,
        and the layout swapped back. Forward-only (`apply_nograd`) —
        gradients always flow through the composition."""
        import jax
        import jax.numpy as jnp

        from paddle_tpu.ops.pallas.conv import _on_tpu, \
            fused_conv_bn_relu

        x = as_tensor(x)
        eps = self.bn._epsilon
        stride = self.conv._stride
        padding = self.conv._padding
        nchw = self._data_format == "NCHW"
        relu = self._act == "relu"
        interpret = not _on_tpu()

        def fn(a, w, gamma, beta, mean, var):
            scale = gamma.astype(jnp.float32) * jax.lax.rsqrt(
                var.astype(jnp.float32) + eps)
            shift = beta.astype(jnp.float32) - \
                mean.astype(jnp.float32) * scale
            if nchw:
                a = jnp.transpose(a, (0, 2, 3, 1))
            wt = jnp.transpose(w, (2, 3, 1, 0))      # OIHW -> HWIO
            out = fused_conv_bn_relu(a, wt, scale, shift,
                                     stride=stride, padding=padding,
                                     relu=relu, interpret=interpret)
            if nchw:
                out = jnp.transpose(out, (0, 3, 1, 2))
            return out

        return apply_nograd("conv_bn_relu_fused", fn, x,
                            self.conv.weight, self.bn.weight,
                            self.bn.bias, self.bn._mean,
                            self.bn._variance)

    def _forward_fused_train(self, x):
        """ONE differentiable dispatch for training: layouts swapped
        to the kernels' NHWC, the `fused_conv_bn_relu_train`
        custom_vjp (batch-stat forward with the stats fused into the
        conv epilogue; fused dInput/dWeight backward), layouts swapped
        back — through `apply`, so the tape (or an outer
        value_and_grad) differentiates straight through the
        custom_vjp. The BN running stats update from the returned
        batch mean/var with exactly the `nn_ops.batch_norm` rule
        (stop-gradient, unbiased variance, momentum)."""
        import jax
        import jax.numpy as jnp

        from paddle_tpu.ops.dispatch import apply
        from paddle_tpu.ops.pallas.conv import _on_tpu, \
            fused_conv_bn_relu_train

        x = as_tensor(x)
        eps = self.bn._epsilon
        stride = self.conv._stride
        padding = self.conv._padding
        nchw = self._data_format == "NCHW"
        relu = self._act == "relu"
        interpret = not _on_tpu()

        def fn(a, w, gamma, beta):
            if nchw:
                a = jnp.transpose(a, (0, 2, 3, 1))
            wt = jnp.transpose(w, (2, 3, 1, 0))      # OIHW -> HWIO
            y, mean, var = fused_conv_bn_relu_train(
                a, wt, gamma, beta, stride=stride, padding=padding,
                relu=relu, eps=eps, interpret=interpret)
            if nchw:
                y = jnp.transpose(y, (0, 3, 1, 2))
            return y, mean, var

        out, mean, var = apply("conv_bn_relu_fused_train", fn, x,
                               self.conv.weight, self.bn.weight,
                               self.bn.bias)
        # running-stat update — the exact nn_ops.batch_norm side
        # effect (under a compiled TrainStep the buffer assignment is
        # captured and persisted like any in-forward buffer write)
        bn = self.bn
        rm, rv = bn._mean._array, bn._variance._array
        os_ = out.shape
        n = float(np.prod([os_[i] for i in ((0, 2, 3) if nchw
                                            else (0, 1, 2))]))
        unbiased = var._array * (n / max(n - 1.0, 1.0))
        mom = bn._momentum
        bn._mean._array = mom * rm + (1 - mom) * \
            jax.lax.stop_gradient(mean._array)
        bn._variance._array = mom * rv + (1 - mom) * \
            jax.lax.stop_gradient(unbiased)
        return out

    def fold(self):
        """Inference-time BN folding: absorb the running-stat affine
        into the conv weights/bias and drop the BN op from forward.
        Idempotent; training after folding would train the folded conv
        against a dead BN, so it flips eval mode on."""
        if self._folded:
            return self
        fold_bn_into_conv(self.conv, self.bn)
        self._folded = True
        self.eval()
        return self


def fold_bn_into_conv(conv, bn):
    """Fold an eval-mode BatchNorm's affine into `conv` IN PLACE:
    w' = w * scale per out-channel, b' = beta - mean*scale (+ old bias
    * scale), with scale = gamma * rsqrt(var + eps) computed in fp64 on
    host so the fold itself adds no low-precision rounding beyond the
    final cast back to the weight dtype."""
    w = conv.weight.numpy().astype(np.float64)          # OIHW
    gamma = bn.weight.numpy().astype(np.float64)
    beta = bn.bias.numpy().astype(np.float64)
    mean = bn._mean.numpy().astype(np.float64)
    var = bn._variance.numpy().astype(np.float64)
    scale = gamma / np.sqrt(var + bn._epsilon)
    shift = beta - mean * scale
    if conv.bias is not None:
        shift = shift + conv.bias.numpy().astype(np.float64) * scale
    wdt = conv.weight.numpy().dtype
    conv.weight.set_value(
        (w * scale[:, None, None, None]).astype(wdt))
    if conv.bias is None:
        # a bias_attr=False conv stored bias=None in the instance
        # __dict__, which would shadow the _parameters registration
        if "bias" in conv.__dict__:
            object.__delattr__(conv, "bias")
        conv.bias = conv.create_parameter(
            [conv._out_channels], is_bias=True)
    conv.bias.set_value(shift.astype(wdt))
    return conv


def fuse_conv_bn(layer):
    """Walk a Layer tree and fold every foldable BatchNorm for eval
    deployment: `ConvBNReLU` blocks fold in place, and any (Conv2D,
    BatchNorm2D) pair ADJACENT in a container's sublayer order (the
    `conv1`/`bn1` stem idiom, `Sequential(conv, bn)` downsamples)
    folds into the conv with the BN replaced by `Identity`. Returns
    the number of BatchNorms folded. Call on an eval-mode model; the
    transform assumes forward applies the BN directly to the conv
    output (true of every pair this repo ships)."""
    n = 0
    if isinstance(layer, ConvBNReLU):
        if not layer._folded and isinstance(layer.bn, BatchNorm2D):
            layer.fold()
            n += 1
        return n
    prev = None
    for name, sub in list(layer._sub_layers.items()):
        if sub is None:
            continue
        if isinstance(sub, BatchNorm2D) and isinstance(prev, Conv2D):
            fold_bn_into_conv(prev, sub)
            layer._sub_layers[name] = Identity()
            prev = None
            n += 1
            continue
        n += fuse_conv_bn(sub)
        prev = sub
    return n
