"""paddle.nn analog (python/paddle/nn/, 35.9k LoC in the reference)."""
from . import functional, initializer
from .activation_layers import (
    CELU, ELU, GELU, SELU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh,
    LeakyReLU, LogSigmoid, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6,
    Sigmoid, Silu, Softmax, Softplus, Softshrink, Softsign, Swish, Tanh,
    Tanhshrink,
)
from .common import (
    CosineSimilarity, Dropout, Dropout2D, Embedding, Flatten, Identity,
    Linear, Pad2D, PixelShuffle, Upsample,
)
from .container import LayerDict, LayerList, ParameterList, Sequential
from .conv import Conv1D, Conv2D, Conv2DTranspose, Conv3D
from .rnn import (GRU, LSTM, RNN, GRUCell, LSTMCell, SimpleRNN,
                  SimpleRNNCell)
from .pooling import MaxPool3D, AvgPool3D  # noqa: F401  (3-D pools)
from .common import Fold, Unfold  # noqa: F401
from .norm import SpectralNorm  # noqa: F401
from .layer import Layer, ParamAttr
from .loss import (
    BCELoss, BCEWithLogitsLoss, CrossEntropyLoss, HingeEmbeddingLoss,
    KLDivLoss, L1Loss, MarginRankingLoss, MSELoss, NLLLoss, SmoothL1Loss,
)
from .norm import (
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm,
    InstanceNorm2D, LayerNorm, LocalResponseNorm, RMSNorm, SyncBatchNorm,
)
from .fused import ConvBNReLU, fold_bn_into_conv, fuse_conv_bn
from .pooling import (
    AdaptiveAvgPool2D, AdaptiveMaxPool2D, AvgPool1D, AvgPool2D, MaxPool1D,
    MaxPool2D,
)
from .transformer import (
    MultiHeadAttention, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer,
)

F = functional

from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: E402
