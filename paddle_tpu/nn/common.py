"""Common layers — analog of python/paddle/nn/layer/common.py."""
from __future__ import annotations

from paddle_tpu.ops import nn_ops
from paddle_tpu.ops import manipulation as mp

from .layer import Layer, ParamAttr


class Linear(Layer):
    """y = xW + b, weight [in_features, out_features] (paddle layout).
    Analog of paddle.nn.Linear (python/paddle/nn/layer/common.py)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr)
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return nn_ops.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return nn_ops.dropout(x, self.p, training=self.training,
                              mode=self.mode, axis=self.axis)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return nn_ops.dropout2d(x, self.p, training=self.training,
                                data_format=self.data_format)


class Embedding(Layer):
    """Analog of paddle.nn.Embedding; lookup compiles to a gather that XLA
    lowers to a TPU-efficient dynamic-slice/one-hot matmul depending on
    size. Weight [num_embeddings, embedding_dim]."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        from paddle_tpu.nn import initializer as I

        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if padding_idx is not None:
            arr = self.weight._array
            self.weight._array = arr.at[padding_idx].set(0.0)

    def forward(self, x):
        return nn_ops.embedding(x, self.weight, padding_idx=self._padding_idx)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return mp.flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.data_format = data_format

    def forward(self, x):
        return nn_ops.interpolate(x, self.size, self.scale_factor, self.mode,
                                  self.align_corners, self.data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return mp.pad(x, self.padding, self.mode, self.value, self.data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW"):
        super().__init__()
        self.upscale_factor = upscale_factor

    def forward(self, x):
        return nn_ops.pixel_shuffle(x, self.upscale_factor)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return nn_ops.cosine_similarity(x1, x2, self.axis, self.eps)


class Unfold(Layer):
    """im2col layer (python/paddle/nn/layer/common.py Unfold)."""

    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.kernel_sizes, self.strides = kernel_sizes, strides
        self.paddings, self.dilations = paddings, dilations

    def forward(self, x):
        return nn_ops.unfold(x, self.kernel_sizes, self.strides,
                             self.paddings, self.dilations)


class Fold(Layer):
    """col2im layer (common.py Fold) — the exact adjoint of Unfold."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes, self.kernel_sizes = output_sizes, kernel_sizes
        self.strides, self.paddings, self.dilations = \
            strides, paddings, dilations

    def forward(self, x):
        return nn_ops.fold(x, self.output_sizes, self.kernel_sizes,
                           self.strides, self.paddings, self.dilations)
