"""Gradient clipping — analog of python/paddle/fluid/clip.py
(ClipGradByGlobalNorm etc.), consumed by optimizer.step. Under hybrid
parallel the mp/pp-aware variant lives in distributed/hybrid_optimizer
(analog of hybrid_parallel_optimizer.py:186's mp-aware clip).
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor._wrap(jnp.clip(g._array, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._array.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor._wrap((g._array * scale).astype(g._array.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        sq = [
            jnp.sum(jnp.square(g._array.astype(jnp.float32)))
            for _, g in params_grads if g is not None
        ]
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor._wrap((g._array * scale).astype(g._array.dtype))))
        return out
