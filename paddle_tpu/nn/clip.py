"""Gradient clipping — analog of python/paddle/fluid/clip.py
(ClipGradByGlobalNorm etc.), consumed by optimizer.step (eager) and by
the compiled steps via `_clip_arrays` (jit.TrainStep,
distributed.DistributedTrainStep). Under SPMD the compiled form IS the
mp/pp-aware clip of hybrid_parallel_optimizer.py:186: the norm reduction
runs on logical global arrays and XLA inserts the mesh collectives.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        arrs = self._clip_arrays([None if g is None else g._array
                                  for _, g in params_grads])
        return [(p, g if a is None else Tensor._wrap(a))
                for (p, g), a in zip(params_grads, arrs)]

    def _clip_arrays(self, grads):
        """jax-traceable form over raw grad arrays (None entries pass
        through) — used INSIDE compiled train steps (TrainStep /
        DistributedTrainStep), where eager Tensor wrapping is wasted work.
        Under pjit the norm reductions run on logical global arrays, so
        XLA inserts the cross-shard collectives — this is the mesh-aware
        clip of hybrid_parallel_optimizer.py:186 for free."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _clip_arrays(self, grads):
        return [None if g is None else jnp.clip(g, self.min, self.max)
                for g in grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_arrays(self, grads):
        out = []
        for g in grads:
            if g is None:
                out.append(None)
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((g * scale).astype(g.dtype))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _clip_arrays(self, grads):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in grads if g is not None]
        if not sq:
            return grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [None if g is None else (g * scale).astype(g.dtype)
                for g in grads]
