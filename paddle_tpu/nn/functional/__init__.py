"""paddle.nn.functional analog — re-exports the functional op surface
(python/paddle/nn/functional/)."""
from paddle_tpu.ops.activation import (
    rrelu, thresholded_relu,
    celu, elu, gelu, glu, gumbel_softmax, hardshrink, hardsigmoid, hardswish,
    hardtanh, leaky_relu, log_sigmoid, log_softmax, maxout, mish, prelu, relu,
    relu6, selu, sigmoid, silu, softmax, softplus, softshrink, softsign,
    swish, tanh, tanhshrink,
)
from paddle_tpu.ops.creation import one_hot
from paddle_tpu.ops.manipulation import pad
from paddle_tpu.ops.nn_ops import (
    adaptive_avg_pool2d, adaptive_max_pool2d, affine_grid, avg_pool1d,
    avg_pool2d, batch_norm, bce_loss, bce_with_logits, conv1d, conv2d,
    conv2d_transpose, conv3d, cosine_similarity, cross_entropy, dropout,
    dropout2d, embedding, fused_bias_dropout_residual_layer_norm, grid_sample,
    group_norm, hinge_embedding_loss, instance_norm, interpolate, kl_div,
    l1_loss, label_smooth, layer_norm, linear, margin_ranking_loss,
    max_pool1d, max_pool2d, mse_loss, nll_loss, pixel_shuffle, rms_norm,
    scaled_dot_product_attention, smooth_l1_loss, softmax_with_cross_entropy,
    temporal_shift, unfold, fold, max_pool3d, avg_pool3d, normalize,
    local_response_norm, dropout3d, alpha_dropout, pixel_unshuffle,
    sequence_mask, square_error_cost, log_loss, sigmoid_focal_loss,
    dice_loss, npair_loss, triplet_margin_loss, cosine_embedding_loss,
    margin_cross_entropy, ctc_loss,
)

binary_cross_entropy = bce_loss
binary_cross_entropy_with_logits = bce_with_logits
upsample = interpolate
