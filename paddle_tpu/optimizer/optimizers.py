"""Concrete optimizers — analogs of python/paddle/optimizer/{sgd,momentum,
adam,adamw,adagrad,rmsprop,adadelta,lamb}.py. Update rules are pure jax
fns compiled (with donation) by the Optimizer base into a single fused
XLA update per step.
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _single_update(self, p, g, acc, lr, step, extras=None):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * p.astype(jnp.float32)
        new_p = p.astype(jnp.float32) - lr * g
        return new_p.astype(p.dtype), {}


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _create_accumulators(self):
        return {"velocity": self._zeros_like_params(jnp.float32)}

    def _single_update(self, p, g, acc, lr, step, extras=None):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * p.astype(jnp.float32)
        v = self._momentum * acc["velocity"] + g
        if self._nesterov:
            upd = g + self._momentum * v
        else:
            upd = v
        new_p = p.astype(jnp.float32) - lr * upd
        return new_p.astype(p.dtype), {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        # reference semantics (optimizer/adam.py multi_precision): True
        # keeps fp32 moments regardless of param dtype (master-precision
        # training of bf16 params — the default and the bench config);
        # False stores moments in the PARAM dtype, halving optimizer
        # HBM traffic for bf16 models at a numerics cost
        self._multi_precision = bool(multi_precision)

    def _create_accumulators(self):
        dt = jnp.float32 if self._multi_precision else None
        return {
            "moment1": self._zeros_like_params(dt),
            "moment2": self._zeros_like_params(dt),
        }

    def _single_update(self, p, g, acc, lr, step, extras=None):
        g = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * pf
        t = (step + 1).astype(jnp.float32)
        m = self._beta1 * acc["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * acc["moment2"] + (1 - self._beta2) * jnp.square(g)
        mhat = m / (1 - jnp.power(self._beta1, t))
        vhat = v / (1 - jnp.power(self._beta2, t))
        new_p = pf - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        # moments re-enter the accumulators at their STORAGE dtype
        # (f32 under multi_precision, else the param dtype) so the
        # compiled step's state threading keeps stable buffer types
        return new_p.astype(p.dtype), {
            "moment1": m.astype(acc["moment1"].dtype),
            "moment2": v.astype(acc["moment2"].dtype)}


class AdamW(Adam):
    """Decoupled weight decay (paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, multi_precision=multi_precision,
                         name=name)
        self._wd = float(weight_decay) if weight_decay else 0.0
        self._apply_decay_param_fun = apply_decay_param_fun
        self._decay_mask = None

    def _ensure_state(self):
        super()._ensure_state()
        if self._decay_mask is None:
            if self._apply_decay_param_fun is not None:
                self._decay_mask = [
                    bool(self._apply_decay_param_fun(p.name))
                    for p in self._parameter_list
                ]
            else:
                self._decay_mask = [True] * len(self._parameter_list)

    def step(self):
        self._ensure_state()
        super().step()

    def _per_param_extras(self, i):
        self._ensure_state()
        return {"decay": jnp.asarray(
            self._wd if self._decay_mask[i] else 0.0, jnp.float32)}

    def _single_update(self, p, g, acc, lr, step, extras=None):
        g = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        wd = extras["decay"] if extras else self._wd
        t = (step + 1).astype(jnp.float32)
        m = self._beta1 * acc["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * acc["moment2"] + (1 - self._beta2) * jnp.square(g)
        mhat = m / (1 - jnp.power(self._beta1, t))
        vhat = v / (1 - jnp.power(self._beta2, t))
        new_p = pf - lr * (mhat / (jnp.sqrt(vhat) + self._epsilon) + wd * pf)
        return new_p.astype(p.dtype), {
            "moment1": m.astype(acc["moment1"].dtype),
            "moment2": v.astype(acc["moment2"].dtype)}


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _create_accumulators(self):
        return {
            "moment": [
                jnp.full(p._array.shape, self._init_acc, jnp.float32)
                for p in self._parameter_list
            ]
        }

    def _single_update(self, p, g, acc, lr, step, extras=None):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * p.astype(jnp.float32)
        mom = acc["moment"] + jnp.square(g)
        new_p = p.astype(jnp.float32) - lr * g / (jnp.sqrt(mom) + self._epsilon)
        return new_p.astype(p.dtype), {"moment": mom}


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _create_accumulators(self):
        out = {
            "mean_square": self._zeros_like_params(jnp.float32),
            "momentum": self._zeros_like_params(jnp.float32),
        }
        if self._centered:
            out["mean_grad"] = self._zeros_like_params(jnp.float32)
        return out

    def _single_update(self, p, g, acc, lr, step, extras=None):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * p.astype(jnp.float32)
        ms = self._rho * acc["mean_square"] + (1 - self._rho) * jnp.square(g)
        out_acc = {"mean_square": ms}
        if self._centered:
            mg = self._rho * acc["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
            out_acc["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * acc["momentum"] + lr * g / denom
        out_acc["momentum"] = mom
        new_p = p.astype(jnp.float32) - mom
        return new_p.astype(p.dtype), out_acc


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self):
        return {
            "avg_squared_grad": self._zeros_like_params(jnp.float32),
            "avg_squared_update": self._zeros_like_params(jnp.float32),
        }

    def _single_update(self, p, g, acc, lr, step, extras=None):
        g = g.astype(jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * p.astype(jnp.float32)
        asg = self._rho * acc["avg_squared_grad"] + (1 - self._rho) * jnp.square(g)
        upd = g * jnp.sqrt(acc["avg_squared_update"] + self._epsilon) / jnp.sqrt(
            asg + self._epsilon)
        asu = self._rho * acc["avg_squared_update"] + (1 - self._rho) * jnp.square(upd)
        new_p = p.astype(jnp.float32) - lr * upd
        return new_p.astype(p.dtype), {
            "avg_squared_grad": asg, "avg_squared_update": asu}


class Lars(Optimizer):
    """LARS — layer-wise adaptive rate scaling for large-batch SGD
    (paddle/incubate/optimizer LarsMomentumOptimizer;
    meta_optimizers/lars_optimizer.py)."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, grad_clip=None, epsilon=1e-9,
                 exclude_from_weight_decay=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._coeff = lars_coeff
        self._wd = lars_weight_decay
        self._epsilon = epsilon
        # substring match on parameter names (paddle Lars semantics:
        # e.g. ['bias', 'bn'] skips decay for biases and batch norms)
        self._exclude = list(exclude_from_weight_decay or [])

    def _create_accumulators(self):
        return {"velocity": self._zeros_like_params(jnp.float32)}

    def _per_param_extras(self, i):
        name = getattr(self._parameter_list[i], "name", None) or ""
        excluded = any(s in name for s in self._exclude)
        return {"decay": jnp.asarray(0.0 if excluded else self._wd,
                                     jnp.float32)}

    def _single_update(self, p, g, acc, lr, step, extras=None):
        wd = extras["decay"] if extras else self._wd
        g = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        w_norm = jnp.sqrt(jnp.sum(jnp.square(pf)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._coeff * w_norm /
            (g_norm + wd * w_norm + self._epsilon),
            1.0)
        v = self._momentum * acc["velocity"] + \
            lr * local_lr * (g + wd * pf)
        new_p = pf - v
        return new_p.astype(p.dtype), {"velocity": v}


class Lamb(Optimizer):
    """LAMB (paddle/optimizer/lamb.py; meta_optimizers/lamb_optimizer.py)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._wd = lamb_weight_decay
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self):
        return {
            "moment1": self._zeros_like_params(jnp.float32),
            "moment2": self._zeros_like_params(jnp.float32),
        }

    def _single_update(self, p, g, acc, lr, step, extras=None):
        g = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        t = (step + 1).astype(jnp.float32)
        m = self._beta1 * acc["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * acc["moment2"] + (1 - self._beta2) * jnp.square(g)
        mhat = m / (1 - jnp.power(self._beta1, t))
        vhat = v / (1 - jnp.power(self._beta2, t))
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + self._wd * pf
        w_norm = jnp.sqrt(jnp.sum(jnp.square(pf)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = pf - lr * trust * r
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}
