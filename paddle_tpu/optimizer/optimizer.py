"""Optimizer base — analog of python/paddle/optimizer/optimizer.py (the
_create_accumulators/_append_optimize_op pattern). TPU-native twist: the
whole update (all params, all accumulators) is ONE jitted pytree function
with donated buffers, so eager `opt.step()` costs a single XLA execution
instead of per-param kernel launches.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if parameters is None:
            raise ValueError("parameters must be provided (dygraph mode)")
        self._parameter_list = list(parameters)
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        if isinstance(weight_decay, float) or isinstance(weight_decay, int):
            self._weight_decay = float(weight_decay)
        else:
            self._weight_decay = weight_decay if weight_decay is None else float(weight_decay)
        # per-parameter accumulator slots: name -> list aligned with params
        self._accumulators: Dict[str, List] = {}
        self._step_count = 0
        self._jitted_update = None

    # -- subclass interface -------------------------------------------------
    def _create_accumulators(self):
        """Return dict name -> list of zero-initialized arrays per param."""
        return {}

    def _single_update(self, param, grad, accums, lr, step, extras=None):
        """Pure function: (param, grad, {name: acc}, lr, step, extras) ->
        (new_param, {name: new_acc}). Must be jax-traceable. `extras` is
        the per-parameter dict from _per_param_extras (e.g. AdamW's decay
        mask)."""
        raise NotImplementedError

    def _per_param_extras(self, i):
        """Per-parameter traced scalars passed to _single_update."""
        return {}

    # -- public api ----------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("can't set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def _ensure_state(self):
        if not self._accumulators and type(self)._create_accumulators is not Optimizer._create_accumulators:
            self._accumulators = self._create_accumulators()

    def _build_jitted_update(self):
        single = self._single_update
        wd = self._weight_decay

        def update_all(params, grads, accums, lr, step, extras):
            new_params, new_accums = [], []
            for i, (p, g) in enumerate(zip(params, grads)):
                acc_i = {k: v[i] for k, v in accums.items()}
                if g is None:
                    new_params.append(p)
                    new_accums.append(acc_i)
                    continue
                np_, na = single(p, g, acc_i, lr, step, extras=extras[i])
                new_params.append(np_)
                new_accums.append(na)
            out_acc = {
                k: [na.get(k, accums[k][i]) for i, na in enumerate(new_accums)]
                for k in accums
            }
            return new_params, out_acc

        # donate param + accumulator buffers: in-place update on TPU HBM
        return jax.jit(update_all, static_argnames=(), donate_argnums=(0, 2))

    @property
    def _params_grads(self):
        pg = []
        for p in self._parameter_list:
            pg.append((p, p.grad))
        return pg

    def step(self):
        self._ensure_state()
        pg = self._params_grads
        if self._grad_clip is not None:
            pg = self._grad_clip(pg)

        params = [p._array for p, _ in pg]
        grads = [g._array if g is not None else None for _, g in pg]
        if builtins_all(g is None for g in grads):
            return
        # jit can't take None leaves in a donated list: substitute zeros mask
        # by splitting indices
        live_idx = [i for i, g in enumerate(grads) if g is not None]
        if self._jitted_update is None:
            self._jitted_update = self._build_jitted_update()

        lr = jnp.asarray(self.get_lr(), jnp.float32)
        step = jnp.asarray(self._step_count, jnp.int32)

        live_params = [params[i] for i in live_idx]
        live_grads = [grads[i] for i in live_idx]
        live_accums = {k: [v[i] for i in live_idx] for k, v in self._accumulators.items()}
        live_extras = [self._per_param_extras(i) for i in live_idx]

        new_params, new_accums = self._jitted_update(
            live_params, live_grads, live_accums, lr, step, live_extras)

        for j, i in enumerate(live_idx):
            self._parameter_list[i]._in_place_update(new_params[j])
            for k in self._accumulators:
                self._accumulators[k][i] = new_accums[k][j]
        self._step_count += 1
        if isinstance(self._learning_rate, LRScheduler):
            pass  # stepping the scheduler is the user's job (paddle semantics)

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    # -- state dict -----------------------------------------------------------
    def state_dict(self):
        self._ensure_state()
        out = {"_step_count": self._step_count}
        import numpy as np

        for k, lst in self._accumulators.items():
            for i, a in enumerate(lst):
                out[f"{k}_{i}"] = Tensor._wrap(a) if a is not None else None
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        self._ensure_state()
        self._step_count = int(state.get("_step_count", 0))
        for k, lst in self._accumulators.items():
            for i in range(len(lst)):
                key = f"{k}_{i}"
                if key in state and state[key] is not None:
                    v = state[key]
                    arr = v._array if isinstance(v, Tensor) else jnp.asarray(v)
                    # coerce to THIS optimizer's configured storage
                    # dtype: a checkpoint saved under a different
                    # multi_precision setting must not silently pin the
                    # old moment dtype (the update casts back to the
                    # accumulator dtype every step)
                    if lst[i] is not None and arr.dtype != lst[i].dtype:
                        arr = arr.astype(lst[i].dtype)
                    lst[i] = arr
        if "LR_Scheduler" in state and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])

    # -- helpers --------------------------------------------------------------
    def _zeros_like_params(self, dtype=None):
        return [
            jnp.zeros(p._array.shape, dtype or p._array.dtype)
            for p in self._parameter_list
        ]


import builtins  # noqa: E402

builtins_all = builtins.all
