from . import lr
from .optimizer import Optimizer
from .optimizers import (
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    AdamW,
    Lamb,
    Lars,
    Momentum,
    RMSProp,
)

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad", "RMSProp",
    "Adadelta", "Lamb", "Lars", "lr",
]
