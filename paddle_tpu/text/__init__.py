"""paddle.text analog (python/paddle/text/): viterbi_decode + a
ViterbiDecoder layer.

TPU-native: the Viterbi dynamic program is two lax.scans (forward
max-product with backpointers, backward path recovery) over the time
axis — fixed shapes, no host loop, batch-vectorized, jittable inside
compiled tagging heads. Reference: python/paddle/text/viterbi_decode.py,
kernel phi/kernels/cpu/viterbi_decode_kernel.cc (start tag = last
transitions row, stop tag = second-to-last column when
include_bos_eos_tag).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

import paddle_tpu.nn as nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.dispatch import apply_nograd

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _viterbi(potentials, trans, lengths, include_bos_eos_tag):
    B, T, N = potentials.shape
    lengths = lengths.astype(jnp.int32)

    alpha0 = potentials[:, 0]
    if include_bos_eos_tag:
        alpha0 = alpha0 + trans[-1][None, :]  # from the start tag

    def fwd(alpha, t):
        # scores[b, i, j] = alpha[b, i] + trans[i, j] + emit[b, t, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)                 # [B, N]
        best_score = jnp.max(scores, axis=1) + potentials[:, t]
        active = (t < lengths)[:, None]
        alpha = jnp.where(active, best_score, alpha)
        return alpha, jnp.where(active, best_prev, -1)

    alpha, backptrs = jax.lax.scan(fwd, alpha0, jnp.arange(1, T))
    # backptrs: [T-1, B, N]
    final = alpha + (trans[:, -2][None, :] if include_bos_eos_tag else 0.0)
    scores = jnp.max(final, axis=1)
    last_tag = jnp.argmax(final, axis=1).astype(jnp.int32)     # [B]

    def bwd(tag, t):
        bp = backptrs[t]                                       # [B, N]
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        # before a sequence's end, follow the pointer; at/after, hold
        follow = (t + 1) < lengths
        new_tag = jnp.where(follow & (prev >= 0),
                            prev.astype(jnp.int32), tag)
        return new_tag, new_tag

    _, rev_path = jax.lax.scan(bwd, last_tag,
                               jnp.arange(T - 2, -1, -1))
    path = jnp.concatenate(
        [jnp.flip(rev_path, axis=0), last_tag[None, :]]).T     # [B, T]
    # zero out positions at/after each sequence's length (kernel parity)
    mask = jnp.arange(T)[None, :] < lengths[:, None]
    path = jnp.where(mask, path, 0)
    return scores, path.astype(jnp.int32)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """(scores [B], paths [B, T]) — highest-scoring tag sequences."""
    return apply_nograd(
        "viterbi_decode",
        lambda p, tr, ln: _viterbi(p, tr, ln, include_bos_eos_tag),
        *(x if isinstance(x, Tensor) else Tensor(x)
          for x in (potentials, transition_params, lengths)))


class ViterbiDecoder(nn.Layer):
    """Layer form (python/paddle/text/viterbi_decode.py:ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


from . import datasets  # noqa: E402
from .datasets import Conll05st, Imdb, UCIHousing  # noqa: E402
