"""Text datasets — analog of python/paddle/text/datasets/ (Imdb,
Conll05st, UCIHousing, ...). Zero-egress build: parsers read LOCAL
files in the published formats; download=True raises (the same policy
as vision/datasets).

- Imdb: aclImdb-style tar.gz (train/{pos,neg}/*.txt), tokenized to a
  frequency-cutoff vocabulary, yields (ids [seq], label).
- Conll05st: tab/space column files (word ... label per line, blank
  line between sentences), yields (word_ids, label_ids).
- UCIHousing: whitespace 14-column regression rows, feature-normalized.
"""
from __future__ import annotations

import os
import re
import tarfile

import numpy as np

from paddle_tpu.io import Dataset

__all__ = ["Imdb", "Conll05st", "UCIHousing"]

_TOKEN_RE = re.compile(r"[A-Za-z0-9']+")


def _no_download(download):
    if download:
        raise RuntimeError(
            "this build has no network egress; place the dataset archive "
            "locally and pass data_file=... (download=False)")


class Imdb(Dataset):
    """IMDB sentiment (text/datasets/imdb.py parity): `data_file` is an
    aclImdb-layout tar.gz; `mode` picks the train/test subtree. Builds
    the vocabulary from the TRAIN split (cutoff by min frequency) and
    encodes each review as int64 ids (unk = len(vocab))."""

    def __init__(self, data_file=None, mode="train", cutoff=1,
                 download=False, seq_len=None):
        _no_download(download)
        if not data_file or not os.path.exists(data_file):
            raise FileNotFoundError(f"Imdb data_file not found: {data_file}")
        self.mode = mode
        self.seq_len = seq_len
        texts = {"train": [], "test": []}
        labels = {"train": [], "test": []}
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                parts = m.name.split("/")
                # .../{train,test}/{pos,neg}/xxx.txt
                if len(parts) < 4 or not m.name.endswith(".txt"):
                    continue
                split, pol = parts[-3], parts[-2]
                if split not in texts or pol not in ("pos", "neg"):
                    continue
                raw = tf.extractfile(m).read().decode("utf-8", "ignore")
                texts[split].append(
                    [t.lower() for t in _TOKEN_RE.findall(raw)])
                labels[split].append(0 if pol == "neg" else 1)
        freq = {}
        for toks in texts["train"]:
            for t in toks:
                freq[t] = freq.get(t, 0) + 1
        vocab_tokens = sorted(t for t, c in freq.items() if c > cutoff)
        self.word_idx = {t: i for i, t in enumerate(vocab_tokens)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [
            np.asarray([self.word_idx.get(t, unk) for t in toks],
                       np.int64)
            for toks in texts[mode]]
        self.labels = np.asarray(labels[mode], np.int64)

    def __getitem__(self, i):
        ids = self.docs[i]
        if self.seq_len is not None:  # pad/trim to fixed length (XLA)
            out = np.full((self.seq_len,), self.word_idx["<unk>"],
                          np.int64)
            n = min(len(ids), self.seq_len)
            out[:n] = ids[:n]
            ids = out
        return ids, self.labels[i]

    def __len__(self):
        return len(self.docs)


class Conll05st(Dataset):
    """CoNLL-style column dataset (text/datasets/conll05.py parity,
    simplified to the word/label columns): `data_file` has one
    "word label" pair per line, blank lines separate sentences."""

    def __init__(self, data_file=None, download=False, seq_len=None):
        _no_download(download)
        if not data_file or not os.path.exists(data_file):
            raise FileNotFoundError(
                f"Conll05st data_file not found: {data_file}")
        sents, tags = [], []
        cur_w, cur_t = [], []
        with open(data_file) as f:
            for line in f:
                line = line.strip()
                if not line:
                    if cur_w:
                        sents.append(cur_w)
                        tags.append(cur_t)
                        cur_w, cur_t = [], []
                    continue
                cols = line.split()
                cur_w.append(cols[0].lower())
                cur_t.append(cols[-1])
        if cur_w:
            sents.append(cur_w)
            tags.append(cur_t)
        words = sorted({w for s in sents for w in s})
        labels = sorted({t for s in tags for t in s})
        self.word_dict = {w: i for i, w in enumerate(words)}
        self.word_dict["<unk>"] = len(self.word_dict)
        self.label_dict = {t: i for i, t in enumerate(labels)}
        # dedicated pad label id — padding must not alias a real class
        self.label_dict["<pad>"] = len(self.label_dict)
        self.seq_len = seq_len
        self._data = [
            (np.asarray([self.word_dict[w] for w in s], np.int64),
             np.asarray([self.label_dict[t] for t in ts], np.int64))
            for s, ts in zip(sents, tags)]

    def __getitem__(self, i):
        ids, labs = self._data[i]
        if self.seq_len is not None:
            unk = self.word_dict["<unk>"]
            out_i = np.full((self.seq_len,), unk, np.int64)
            out_l = np.full((self.seq_len,),
                            self.label_dict["<pad>"], np.int64)
            n = min(len(ids), self.seq_len)
            out_i[:n] = ids[:n]
            out_l[:n] = labs[:n]
            return out_i, out_l
        return ids, labs

    def __len__(self):
        return len(self._data)


class UCIHousing(Dataset):
    """Boston-housing-format regression rows (text/datasets/
    uci_housing.py parity): 14 whitespace columns, features normalized
    to zero mean / unit std over the file, last column is the target."""

    def __init__(self, data_file=None, mode="train", download=False):
        _no_download(download)
        if not data_file or not os.path.exists(data_file):
            raise FileNotFoundError(
                f"UCIHousing data_file not found: {data_file}")
        rows = np.loadtxt(data_file).astype(np.float32)
        if rows.ndim == 1:
            rows = rows[None]
        x = rows[:, :-1]
        mu, sd = x.mean(axis=0), x.std(axis=0) + 1e-8
        x = (x - mu) / sd
        split = int(len(rows) * 0.8)
        sl = np.s_[:split] if mode == "train" else np.s_[split:]
        self.x = x[sl]
        self.y = rows[:, -1:][sl]

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)
