"""The tpu-lint rule set (TPU001..TPU008).

Each rule is a function Module -> [Finding]. Registries of names
(trace entries, collectives, samplers, contraction ops) come from
`paddle_tpu.jit.introspect` — the jit layer's own metadata.

TPU003/TPU004 run a small branch-aware linear interpreter over each
function body: `if`/`else` branches execute on copies of the state and
merge (branches that terminate in return/raise don't merge back), loop
bodies execute twice so loop-carried hazards (a key consumed on
iteration 1 and again on iteration 2, a buffer donated then read at
the top of the next iteration) surface, with findings deduplicated by
position.
"""
from __future__ import annotations

import ast

from paddle_tpu.jit import introspect as I

_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "remove", "discard", "clear", "pop", "popitem", "appendleft"}

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


def _owned_calls(fi):
    return [n for n in fi.nodes if isinstance(n, ast.Call)]


# ---------------------------------------------------------------------------
# TPU001 — host sync inside traced code
# ---------------------------------------------------------------------------

def rule_tpu001(m):
    out = []
    for fi in m.traced_functions():
        m.func_taint(fi)
        for node in _owned_calls(fi):
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in I.HOST_SYNC_METHODS and \
                    m.expr_taint(f.value, fi):
                out.append(m.finding(
                    "TPU001", node,
                    f"`.{f.attr}()` on a traced value forces a "
                    "device->host sync inside traced code (blocks "
                    "dispatch or fails to trace); keep the value on "
                    "device or move the sync outside the jitted fn",
                    fi))
                continue
            name = m.resolve(f)
            if name in I.HOST_SYNC_CALLS and any(
                    m.expr_taint(a, fi) for a in node.args):
                out.append(m.finding(
                    "TPU001", node,
                    f"`{name}` concretizes a traced value on host "
                    "inside traced code; use jnp ops instead", fi))
            elif name in I.HOST_SYNC_BUILTINS and node.args and \
                    m.expr_taint(node.args[0], fi):
                out.append(m.finding(
                    "TPU001", node,
                    f"`{name}()` of a traced value raises "
                    "ConcretizationError under jit; keep it as a "
                    "0-d array (or hoist the scalar out of the "
                    "traced fn)", fi))
            elif name == "print" and any(
                    m.expr_taint(a, fi) for a in node.args):
                out.append(m.finding(
                    "TPU001", node,
                    "`print` of a traced value runs once at trace "
                    "time (and syncs if it concretizes); use "
                    "jax.debug.print", fi))
    return out


# ---------------------------------------------------------------------------
# TPU002 — python control flow on traced booleans
# ---------------------------------------------------------------------------

def rule_tpu002(m):
    out = []
    for fi in m.traced_functions():
        if fi.dy2static:
            # to_static runs the dy2static AST pass: tracer if/while
            # become lax.cond/while_loop in the wrapped fn itself
            continue
        m.func_taint(fi)
        for node in fi.nodes:
            if isinstance(node, (ast.If, ast.While)) and \
                    m.expr_taint(node.test, fi):
                kind = "if" if isinstance(node, ast.If) else "while"
                fix = "lax.cond/jnp.where" if kind == "if" \
                    else "lax.while_loop"
                out.append(m.finding(
                    "TPU002", node,
                    f"python `{kind}` on a traced value retraces per "
                    "value or raises ConcretizationError; use "
                    f"{fix} (or mark the arg static)", fi))
            elif isinstance(node, ast.Assert) and \
                    m.expr_taint(node.test, fi):
                out.append(m.finding(
                    "TPU002", node,
                    "`assert` on a traced value concretizes under "
                    "jit; use checkify or debug.check, or assert "
                    "outside the traced fn", fi))
    return out


# ---------------------------------------------------------------------------
# linear branch-aware walkers (TPU003 / TPU004)
# ---------------------------------------------------------------------------

class _LinearRule:
    """Executes a function body statement-by-statement with a dict
    state; If branches fork+merge, loop bodies run twice."""

    def __init__(self, module, fi):
        self.m = module
        self.fi = fi
        self.findings = []
        self._reported = set()

    def report(self, rule, node, message):
        key = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0),
               rule)
        if key not in self._reported:
            self._reported.add(key)
            self.findings.append(
                self.m.finding(rule, node, message, self.fi))

    def run(self):
        body = getattr(self.fi.node, "body", [])
        if not isinstance(body, list):   # lambda
            body = [ast.Expr(value=body)]
        self.exec_block(body, self.initial())
        return self.findings

    def initial(self):
        return {}

    @staticmethod
    def merge(state, branches):
        for b in branches:
            for k, v in b.items():
                state.setdefault(k, v)
        return state

    def exec_block(self, stmts, state):
        """Returns True when the block unconditionally terminates."""
        for stmt in stmts:
            if isinstance(stmt, _SCOPE_NODES):
                continue   # nested scopes analyzed separately
            if isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    self.scan_expr(stmt.value, state)
                return True
            if isinstance(stmt, ast.Raise):
                if stmt.exc is not None:
                    self.scan_expr(stmt.exc, state)
                return True
            if isinstance(stmt, (ast.Break, ast.Continue)):
                return True
            if isinstance(stmt, ast.If):
                self.scan_expr(stmt.test, state)
                s_body, s_else = dict(state), dict(state)
                t_body = self.exec_block(stmt.body, s_body)
                t_else = self.exec_block(stmt.orelse, s_else)
                live = [s for s, t in ((s_body, t_body), (s_else, t_else))
                        if not t]
                if not live:
                    return True
                state.clear()
                state.update(live[0])
                self.merge(state, live[1:])
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self.scan_expr(stmt.iter, state)
                self.on_store(stmt.target, state)
                self.exec_block(stmt.body, state)
                self.on_store(stmt.target, state)
                self.exec_block(stmt.body, state)   # loop-carried pass
                self.exec_block(stmt.orelse, state)
                continue
            if isinstance(stmt, ast.While):
                self.scan_expr(stmt.test, state)
                self.exec_block(stmt.body, state)
                self.scan_expr(stmt.test, state)
                self.exec_block(stmt.body, state)
                self.exec_block(stmt.orelse, state)
                continue
            if isinstance(stmt, ast.Try):
                t = self.exec_block(stmt.body, state)
                branches = []
                for h in stmt.handlers:
                    s_h = dict(state)
                    self.exec_block(h.body, s_h)
                    branches.append(s_h)
                self.merge(state, branches)
                if not t:
                    self.exec_block(stmt.orelse, state)
                self.exec_block(stmt.finalbody, state)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self.scan_expr(item.context_expr, state)
                    if item.optional_vars is not None:
                        self.on_store(item.optional_vars, state)
                self.exec_block(stmt.body, state)
                continue
            self.exec_stmt(stmt, state)
        return False

    def exec_stmt(self, stmt, state):
        if isinstance(stmt, ast.Assign):
            self.scan_expr(stmt.value, state)
            self.on_assign(stmt.targets, stmt.value, state)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.scan_expr(stmt.value, state)
                self.on_assign([stmt.target], stmt.value, state)
        elif isinstance(stmt, ast.AugAssign):
            self.scan_expr(stmt.value, state)
            self.on_store(stmt.target, state)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self.on_store(t, state)
        elif isinstance(stmt, ast.Expr):
            self.scan_expr(stmt.value, state)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self.scan_expr(child, state)

    def scan_expr(self, e, state):
        if e is None or isinstance(e, ast.Lambda):
            return
        if isinstance(e, ast.IfExp):
            self.scan_expr(e.test, state)
            s1, s2 = dict(state), dict(state)
            self.scan_expr(e.body, s1)
            self.scan_expr(e.orelse, s2)
            state.clear()
            state.update(s1)
            self.merge(state, [s2])
            return
        if isinstance(e, ast.BoolOp):
            self.scan_expr(e.values[0], state)
            rest = []
            for v in e.values[1:]:
                s = dict(state)
                self.scan_expr(v, s)
                rest.append(s)
            self.merge(state, rest)
            return
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            for g in e.generators:
                self.scan_expr(g.iter, state)
            bodies = [e.key, e.value] if isinstance(e, ast.DictComp) \
                else [e.elt]
            for _ in range(2):   # comp body runs per-iteration
                for b in bodies:
                    self.scan_expr(b, state)
            return
        if isinstance(e, ast.Call):
            self.scan_expr(e.func, state)
            for a in e.args:
                self.scan_expr(a, state)
            for kw in e.keywords:
                self.scan_expr(kw.value, state)
            self.on_call(e, state)
            return
        self.on_expr(e, state)
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self.scan_expr(child, state)

    def on_assign(self, targets, value, state):
        for t in targets:
            self.on_store(t, state)

    def on_store(self, target, state):
        for name in self.m._target_names(target):
            self.clear_name(name, state)

    # hooks
    def clear_name(self, name, state):
        state.pop(name, None)

    def on_call(self, call, state):
        pass

    def on_expr(self, e, state):
        pass


class _KeyReuse(_LinearRule):
    """TPU003: a PRNG key variable consumed by two sampling ops without
    an intervening split/reassignment."""

    def on_call(self, call, state):
        name = self.m.resolve(call.func)
        if not name:
            return
        ns = next((p for p in I.RANDOM_NAMESPACES
                   if name.startswith(p)), None)
        if ns is None:
            return
        leaf = name[len(ns):]
        if "." in leaf or leaf in I.RANDOM_KEY_DERIVERS:
            return
        key_arg = call.args[0] if call.args else next(
            (kw.value for kw in call.keywords if kw.arg == "key"), None)
        if not isinstance(key_arg, ast.Name):
            return
        k = key_arg.id
        if k in state:
            self.report(
                "TPU003", call,
                f"PRNG key `{k}` already consumed by a sampler at line "
                f"{state[k]} — reusing it makes correlated randomness; "
                "jax.random.split (or fold_in) first")
        else:
            state[k] = call.lineno


class _DonatedUse(_LinearRule):
    """TPU004: an argument passed at a donate_argnums position is read
    again after the donating call (its buffer is invalid)."""

    def initial(self):
        return {"jit": {}, "donated": {}, "layouts": {}}

    @staticmethod
    def merge(state, branches):
        for b in branches:
            for k in ("jit", "donated", "layouts"):
                for name, v in b.get(k, {}).items():
                    state[k].setdefault(name, v)
        return state

    def clear_name(self, name, state):
        state["jit"].pop(name, None)
        state["donated"].pop(name, None)
        state["layouts"].pop(name, None)

    def _positions_from(self, val, state):
        """Donation positions of one expression: int/tuple literals,
        the `X if flag else ()` idiom, `introspect.*_DONATE_ARGNUMS`
        constants, or a local name previously bound to any of those."""
        if isinstance(val, ast.IfExp):
            return self._positions_from(val.body, state) or \
                self._positions_from(val.orelse, state)
        if isinstance(val, ast.Constant) and isinstance(val.value, int):
            return (val.value,)
        if isinstance(val, (ast.Tuple, ast.List)):
            out = tuple(e.value for e in val.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int))
            return out or None
        if isinstance(val, ast.Name) and val.id in state["layouts"]:
            return state["layouts"][val.id]
        name = self.m.resolve(val)
        if name:
            leaf = name.rsplit(".", 1)[-1]
            if leaf in I.DONATION_CONSTANTS and \
                    (name == leaf or ".introspect." in f".{name}"):
                return I.DONATION_CONSTANTS[leaf]
        return None

    def _donate_positions(self, call, state):
        for kw in call.keywords:
            if kw.arg in I.DONATE_ARG_KEYWORDS:
                out = self._positions_from(kw.value, state)
                if out:
                    return out
        return None

    def _donate_args(self, call, positions, state):
        for pos in positions:
            if pos < len(call.args) and \
                    isinstance(call.args[pos], ast.Name):
                name = call.args[pos].id
                state["donated"][name] = call.lineno

    def on_expr(self, e, state):
        if isinstance(e, ast.Name) and isinstance(e.ctx, ast.Load) and \
                e.id in state["donated"]:
            line = state["donated"].pop(e.id)
            self.report(
                "TPU004", e,
                f"`{e.id}` was donated to the jitted call at line "
                f"{line} (donate_argnums) — its buffer is invalid "
                "here; use the call's RESULT or drop the donation")

    def on_call(self, call, state):
        f = call.func
        if isinstance(f, ast.Name) and f.id in state["jit"]:
            self._donate_args(call, state["jit"][f.id], state)
            return
        # immediate form: jax.jit(f, donate_argnums=...)(args)
        if isinstance(f, ast.Call) and \
                self.m.resolve(f.func) in I.JIT_LIKE:
            positions = self._donate_positions(f, state)
            if positions:
                self._donate_args(call, positions, state)

    def on_assign(self, targets, value, state):
        super().on_assign(targets, value, state)
        if len(targets) != 1 or not isinstance(targets[0], ast.Name):
            return
        if isinstance(value, ast.Call) and \
                self.m.resolve(value.func) in I.JIT_LIKE:
            positions = self._donate_positions(value, state)
            if positions:
                state["jit"][targets[0].id] = positions
        else:
            # donate = introspect.TRAINSTEP_DONATE_ARGNUMS if ... else ()
            positions = self._positions_from(value, state)
            if positions:
                state["layouts"][targets[0].id] = positions


def rule_tpu003(m):
    out = []
    for fi in m.functions:
        out.extend(_KeyReuse(m, fi).run())
    return out


def rule_tpu004(m):
    out = []
    for fi in m.functions:
        out.extend(_DonatedUse(m, fi).run())
    return out


# ---------------------------------------------------------------------------
# TPU005 — python side effects under trace
# ---------------------------------------------------------------------------

def _bound_outward(fi, name, m):
    scope = fi.parent
    while scope is not None:
        if name in scope.local_bindings or name in scope.children:
            return True
        scope = scope.parent
    return name in m.aliases


def rule_tpu005(m):
    out = []
    for fi in m.traced_functions():
        for node in fi.nodes:
            if isinstance(node, ast.Call):
                f = node.func
                name = m.resolve(f)
                if name in I.IMPURE_CALLS or (name and any(
                        name.startswith(p)
                        for p in I.IMPURE_CALL_PREFIXES)):
                    out.append(m.finding(
                        "TPU005", node,
                        f"`{name}` inside traced code runs ONCE at "
                        "trace time and bakes a constant into the "
                        "compiled program; hoist it out (or pass the "
                        "value in as an argument)", fi))
                elif isinstance(f, ast.Attribute) and \
                        f.attr in _MUTATORS and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id not in fi.local_bindings and \
                        _bound_outward(fi, f.value.id, m):
                    out.append(m.finding(
                        "TPU005", node,
                        f"mutating closed-over `{f.value.id}` inside "
                        "traced code happens once at trace time, not "
                        "per step; return the value instead", fi))
            elif isinstance(node, ast.Assign) and fi.global_names:
                hit = [n for t in node.targets
                       for n in m._target_names(t)
                       if n in fi.global_names]
                if hit:
                    out.append(m.finding(
                        "TPU005", node,
                        f"assigning global `{hit[0]}` inside traced "
                        "code happens once at trace time; return the "
                        "value instead", fi))
    return out


# ---------------------------------------------------------------------------
# TPU006 — unordered iteration building ordered structures
# ---------------------------------------------------------------------------

def _set_names(m, fi):
    """Names in this scope that only ever hold set values."""
    setlike, other = set(), set()
    for node in fi.nodes:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            (setlike if _is_setlike(m, node.value, ())
             else other).add(node.targets[0].id)
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and node.value:
            (setlike if _is_setlike(m, node.value, ())
             else other).add(node.target.id)
    return setlike - other


def _is_setlike(m, e, set_names):
    if isinstance(e, (ast.Set, ast.SetComp)):
        return True
    if isinstance(e, ast.Name):
        return e.id in set_names
    if isinstance(e, ast.Call):
        return m.resolve(e.func) in ("set", "frozenset")
    if isinstance(e, ast.BinOp) and isinstance(
            e.op, (ast.Sub, ast.BitOr, ast.BitAnd, ast.BitXor)):
        return _is_setlike(m, e.left, set_names) or \
            _is_setlike(m, e.right, set_names)
    return False


def _builds_ordered(body):
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("append", "extend", "insert"):
                return True
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Subscript) for t in node.targets):
                return True
            if isinstance(node, (ast.Yield, ast.YieldFrom)):
                return True
    return False


_MSG006 = ("iterating a set here feeds an ORDERED structure: python "
           "set order varies across processes (hash seed), so pytree "
           "flatten order / param dicts diverge across ranks; iterate "
           "sorted(...) instead")


#: Consumers whose result does not depend on iteration order — a
#: comprehension over a set fed DIRECTLY to one of these is fine
#: (mirrors the for-loop branch's _builds_ordered gate).
_ORDER_FREE_CONSUMERS = {"any", "all", "sum", "min", "max", "len",
                         "set", "frozenset", "sorted"}


def rule_tpu006(m):
    out = []
    for fi in m.functions:
        names = _set_names(m, fi)
        order_free = set()
        for node in fi.nodes:
            if isinstance(node, ast.Call) and \
                    m.resolve(node.func) in _ORDER_FREE_CONSUMERS:
                order_free.update(id(a) for a in node.args)
        for node in fi.nodes:
            if isinstance(node, (ast.For, ast.AsyncFor)):
                if _is_setlike(m, node.iter, names) and \
                        _builds_ordered(node.body):
                    out.append(m.finding("TPU006", node, _MSG006, fi))
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                   ast.DictComp)):
                if id(node) not in order_free and \
                        any(_is_setlike(m, g.iter, names)
                            for g in node.generators):
                    out.append(m.finding("TPU006", node, _MSG006, fi))
    return out


# ---------------------------------------------------------------------------
# TPU007 — eager collectives under trace
# ---------------------------------------------------------------------------

def rule_tpu007(m):
    out = []
    eager = {p + n for p in I.EAGER_COLLECTIVE_PREFIXES
             for n in I.EAGER_COLLECTIVES}
    for fi in m.traced_functions():
        for node in _owned_calls(fi):
            name = m.resolve(node.func)
            if name in eager:
                out.append(m.finding(
                    "TPU007", node,
                    f"`{name}` is an EAGER collective (runs its own "
                    "compiled program and blocks the host) — inside "
                    "traced code use mesh primitives (jax.lax.psum / "
                    "shard_map) or thread it through the spmd step",
                    fi))
    return out


# ---------------------------------------------------------------------------
# TPU008 — contraction without pinned accumulator dtype in bf16 paths
# ---------------------------------------------------------------------------

_LOOP_BODY_VIAS = ("jax.lax.scan", "jax.lax.fori_loop",
                   "jax.lax.while_loop", "jax.lax.map",
                   "jax.lax.associative_scan")


def _unwrap_cast(e):
    """`einsum(...).astype(t)` — look through the cast to the
    contraction."""
    if isinstance(e, ast.Call) and isinstance(e.func, ast.Attribute) \
            and e.func.attr == "astype":
        return e.func.value
    return e


def rule_tpu008(m):
    """A contraction without a pinned accumulator dtype is only the
    PR-3 bug class when its OUTPUT is accumulated: summed with a
    running value (`acc + einsum(...)`, `acc += ...`) or recomputed
    per iteration of a loop body (python loop or a staged
    lax.scan/fori_loop body). A standalone bf16 matmul accumulates
    inside the MXU at fp32 and is fine."""
    out = []
    for fi in m.functions:
        if not fi.effective_bf16():
            continue
        cands = {}
        for node in fi.nodes:
            if isinstance(node, ast.Call):
                name = m.resolve(node.func)
                if name in I.CONTRACTION_CALLS and not any(
                        kw.arg == I.ACCUM_DTYPE_KEYWORD
                        for kw in node.keywords):
                    cands[id(node)] = (node, name)
            elif isinstance(node, ast.BinOp) and \
                    isinstance(node.op, ast.MatMult):
                cands[id(node)] = (node, "@")
        if not cands:
            continue
        accumulating = set()
        if fi.trace_via in _LOOP_BODY_VIAS:
            accumulating |= set(cands)          # staged loop body
        for node in fi.nodes:
            if isinstance(node, ast.BinOp) and isinstance(node.op,
                                                          ast.Add):
                for side in (node.left, node.right):
                    side = _unwrap_cast(side)
                    if id(side) in cands:
                        accumulating.add(id(side))
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.op, ast.Add):
                for sub in ast.walk(node.value):
                    if id(sub) in cands:
                        accumulating.add(id(sub))
            elif isinstance(node, (ast.For, ast.While)):
                for stmt in node.body:
                    for sub in ast.walk(stmt):
                        if id(sub) in cands:
                            accumulating.add(id(sub))
        # iterate cands (AST-walk order), not the set — finding order
        # must be deterministic (tpu-lint's own TPU006)
        for key, (node, name) in cands.items():
            if key not in accumulating:
                continue
            what = "`@` matmul" if name == "@" else f"`{name}`"
            out.append(m.finding(
                "TPU008", node,
                f"{what} output is ACCUMULATED in a bf16 code path "
                f"without `{I.ACCUM_DTYPE_KEYWORD}` — partial sums at "
                "bf16 cancel catastrophically (the paged-attention PV "
                "bug class); pin jnp.float32 and cast once after the "
                "accumulation", fi))
    return out


RULES = {
    "TPU000": ("parse-error",
               "file could not be parsed (reported, never skipped)",
               None),
    "TPU001": ("host-sync-in-trace",
               "device->host sync (.item/.tolist/.numpy, float/int, "
               "np.asarray, print) of a traced value inside traced code",
               rule_tpu001),
    "TPU002": ("python-branch-on-tracer",
               "python if/while/assert on a traced boolean — "
               "recompile or ConcretizationError hazard",
               rule_tpu002),
    "TPU003": ("prng-key-reuse",
               "same PRNG key consumed by two samplers without an "
               "intervening split",
               rule_tpu003),
    "TPU004": ("donated-buffer-use",
               "argument at a donate_argnums position read after the "
               "donating call",
               rule_tpu004),
    "TPU005": ("side-effect-in-trace",
               "python side effects under trace (closure/global "
               "mutation, wall-clock, python RNG)",
               rule_tpu005),
    "TPU006": ("unordered-iteration",
               "iterating a set into an ordered structure — "
               "nondeterministic flatten order across ranks",
               rule_tpu006),
    "TPU007": ("eager-collective-in-trace",
               "eager paddle_tpu.distributed collective called from "
               "traced code",
               rule_tpu007),
    "TPU008": ("accum-dtype-trap",
               "contraction without preferred_element_type in a bf16 "
               "code path",
               rule_tpu008),
}


def all_rule_ids():
    return sorted(RULES)
