"""Harvest: abstractly trace every registered compiled engine program
over the serving config matrix, on CPU, with no device execution.

For each matrix point ({dense,pallas} x K in {0,4} x mp in {1,2}) a
TINY GPT engine is constructed exactly the way serving constructs it
(same builders, same jit wrappers, same donation/out_shardings — the
checker lowers the ENGINE'S OWN jitted objects, so a contract break in
`inference/engine.py` cannot hide behind a checker-side rebuild), its
step bodies are traced with `jax.make_jaxpr` and lowered with
`.lower()`, and the TPU1xx rules run over the resulting
jaxpr/StableHLO. Tracing and lowering never dispatch a computation;
the only device interaction is allocating the tiny engine's zeroed
pools, which is why the whole matrix runs in CPU-only CI.

The committed `TRACE_BASELINE.json` (repo root, next to the other
baselines) snapshots per-program op/collective/byte counts; any drift
is a TPU100 finding — an intentional change regenerates it with
`tools/tpu_verify.py --write-trace-baseline` and reviews the diff.

jax / the framework are imported INSIDE the functions here: importing
`paddle_tpu.analysis.trace` must not initialize a JAX backend (the
import-smoke contract).
"""
from __future__ import annotations

import json
import os

from ..findings import Finding, assign_ids
from .contracts import get_contract
from .rules import TracedProgram, check_program

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

#: Committed drift snapshot (repo root, BENCH_BASELINE.json precedent).
DEFAULT_TRACE_BASELINE = os.path.join(_REPO_ROOT, "TRACE_BASELINE.json")

#: The serving config matrix every contract is checked under.
BACKENDS = ("dense", "pallas")
SPEC_KS = (0, 4)
MP_DEGREES = (1, 2)
#: None = today's fp serving; "int8" = the quantized configs (int8
#: per-block-scaled KV pools AND int8 weights through the state seam)
#: — every contract is proven over both, so a quantization regression
#: (dropped donation, bf16 accumulation on a dequantized matmul, an
#: unbudgeted collective in the scale fold) fails the same gate.
KV_DTYPES = (None, "int8")

#: Multi-tenant LoRA configs (PR 13): the base matrix threads NO
#: adapter state (its programs must stay byte-identical to the
#: pre-adapter baseline), and these two extra configs prove the
#: adapter-threaded steps — a plain fp mp=1 decode+prefill pass and
#: the fully-composed (pallas, K=4, mp=2, int8) verify step — under
#: every TPU1xx rule: donation still pins both pools, the lora
#: einsums accumulate fp32 (TPU103), and the adapter gathers add NO
#: collectives (TPU104's budget is unchanged).
LORA_CONFIGS = (("dense", 0, 1, None, True),
                ("pallas", 4, 2, "int8", True))

#: Probabilistic serving configs (PR 15): the base matrix threads NO
#: sampling state (a sampling=False engine's programs must stay
#: byte-identical to the pre-sampling baseline — the greedy
#: no-regression proof at the trace level), and these two extra
#: configs prove the sampling-threaded steps — a plain fp mp=1
#: decode+prefill pass and the fully-composed (pallas, K=4, mp=2,
#: int8) REJECTION-SAMPLING verify step — under every TPU1xx rule:
#: donation still pins both pools, the draw/masking math stays fp32
#: (TPU103), and the per-slot key folds add NO collectives (TPU104's
#: budget is unchanged — the draws run replicated on the all-gathered
#: logits).
SAMPLING_CONFIGS = (("dense", 0, 1, None, False, True),
                    ("pallas", 4, 2, "int8", False, True))

#: Tiny-but-structurally-real harvest geometry: 2 layers so per-layer
#: collective budgets multiply, 4 heads so mp=2 head-sharding divides,
#: block_size 8 so the pallas kernel's sublane constraint holds.
TINY = dict(vocab=64, hidden=32, layers=2, heads=4, seq=32,
            slots=2, block_size=8, max_rank=4)


def default_matrix():
    return tuple((b, k, mp, kv, False, False) for b in BACKENDS
                 for k in SPEC_KS for mp in MP_DEGREES
                 for kv in KV_DTYPES) \
        + tuple((*m, False) for m in LORA_CONFIGS) + SAMPLING_CONFIGS


def _require_devices(mp):
    import jax

    if mp > 1 and len(jax.devices()) < mp:
        raise RuntimeError(
            f"harvesting the mp={mp} configs needs {mp}+ devices — "
            "set XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "BEFORE the first jax use (tools/tpu_verify.py does this "
            "for you)")


def _build_model():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig.tiny(vocab=TINY["vocab"], hidden=TINY["hidden"],
                         layers=TINY["layers"], heads=TINY["heads"],
                         seq=TINY["seq"])
    model = GPTForCausalLM(cfg)
    model.eval()
    return model


def _trace_one(name, config, pure_fn, jitted, args, mp, num_layers,
               declared=None, geometry=None):
    """make_jaxpr + lower ONE program and capture the TracedProgram
    record the rules consume. `jitted` is the engine's own jit wrapper
    (its donation and out_shardings, not the checker's). `declared` is
    an optional (in_specs, out_specs) pair of per-leaf layout tuples
    (see `_declared_specs`) and `geometry` the serving-symbol dict —
    both consumed by the tpu-shard tier."""
    import jax

    contract = get_contract(name)
    closed = jax.make_jaxpr(pure_fn)(*args)
    lowered = jitted.lower(*args)
    donated = sum(
        len(jax.tree_util.tree_leaves(args[i]))
        for i in contract.donate_argnums)
    leaves = [(jax.tree_util.keystr(path), leaf) for path, leaf in
              jax.tree_util.tree_flatten_with_path(args)[0]]
    d_in, d_out = declared if declared is not None else (None, None)
    return TracedProgram(
        contract=contract, config=config, mp=mp,
        num_layers=num_layers, jaxpr=closed,
        lowered_text=lowered.as_text(), donated_leaves=donated,
        arg_leaves=leaves, declared_in_specs=d_in,
        declared_out_specs=d_out, geometry=geometry)


def _declared_specs(eng, args, kv, lora, n_out_repl):
    """The engine's DECLARED layout truth for one step, flattened per
    argument leaf in signature order: `_tp_specs` for the state
    (quantized entries contribute their (codes, scale) spec pair),
    `pool_pspec()` for both pool planes, a replicated spec for the
    int8 scale grid, the adapter pool's `pool_pspecs()`, and None
    (no declaration) for the trailing host args. Outputs mirror
    `_step_out_shardings`: `n_out_repl` replicated leading outputs,
    then the sharded pools, then the replicated scale grid. Specs are
    converted to pure per-dim axis-name tuples (() = replicated) so
    the tpu-shard rules never import jax. None/None at mp == 1 —
    there is no declared mesh layout to drift from."""
    if eng.mesh is None:
        return None, None
    import jax
    from jax.sharding import PartitionSpec as P

    ins = []
    for spec in eng._tp_specs:
        pair = (spec,) if isinstance(spec, P) else tuple(spec)
        ins.extend(tuple(s) for s in pair)
    pool = tuple(eng.cache.pool_pspec())
    ins += [pool, pool]
    if kv:
        ins.append(())
    if lora:
        ins.extend(tuple(s) for s in eng.adapter_pool.pool_pspecs())
    n_host = len(jax.tree_util.tree_leaves(args)) - len(ins)
    assert n_host >= 0, "declared specs outnumber the program's leaves"
    out_specs = ((),) * n_out_repl + (pool, pool) \
        + (((),) if kv else ())
    return tuple(ins) + (None,) * n_host, out_specs


def _geometry(eng, num_layers, tokens):
    """The serving-geometry symbols tpu-shard's payload bounds
    (AxisCollectiveBudget entries) evaluate over — from the engine
    and model the program was actually traced from."""
    cfg = eng.model.config
    return dict(tokens=tokens, hidden=cfg.hidden_size,
                intermediate=cfg.intermediate_size,
                vocab=cfg.vocab_size, heads=cfg.num_heads,
                head_dim=cfg.hidden_size // cfg.num_heads,
                layers=num_layers, blocks=eng.cache.num_blocks,
                block_size=eng.cache.block_size,
                slots=eng.num_slots)


def _build_registry(config):
    """A tiny one-adapter registry for the LoRA configs: shapes are
    all abstract tracing sees, so the factors are zero-filled."""
    import numpy as np

    from paddle_tpu.adapters import AdapterRegistry

    reg = AdapterRegistry(config, max_rank=TINY["max_rank"])
    r, L = 2, config.num_layers
    weights = {}
    for site in ("qkv", "out", "fc1", "fc2"):
        in_d, out_d = reg.site_dims(site)
        weights[site] = [(np.zeros((r, in_d), np.float32),
                          np.zeros((out_d, r), np.float32))
                         for _ in range(L)]
    reg.register(1, weights, scaling=0.5)
    return reg


def harvest(matrix=None):
    """-> list[TracedProgram] over the full contract matrix: one
    chunked engine per (backend, K, mp, kv_dtype) contributes its
    decode-or-verify step (16 programs — where the backends/K/kv
    diverge); the backend/K-invariant programs (chunked prefill,
    legacy bucketed prefill from a bucketed engine, COW block-copy)
    harvest once per (mp, kv_dtype) (12 more). The kv="int8" configs
    serve int8 per-block-scaled KV AND int8 weights — the full
    quantized serving shape. The LORA_CONFIGS entries add the
    adapter-threaded programs (4 more: a dense mp=1 decode + both
    prefills, and the composed pallas/K=4/mp=2/int8 verify); the
    SAMPLING_CONFIGS entries add the sampling-threaded programs
    (4 more: a dense mp=1 sampled decode + both sampled prefills, and
    the composed pallas/K=4/mp=2/int8 REJECTION-SAMPLING verify). The
    default (full) harvest also carries the fused Pallas conv suite's
    4 programs (`_conv_programs`) so their lowering is drift-gated
    like every engine step."""
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.inference.engine import GenerationEngine

    include_conv = matrix is None
    # pad short (pre-sampling / pre-lora) matrix entries with the
    # DEFAULTS for the missing trailing fields — positional slicing
    # would hand a 5-tuple samp=None and trip check_knobs
    matrix = default_matrix() if matrix is None else tuple(
        (*m, *(None, False, False)[len(m) - 3:]) if len(m) < 6 else m
        for m in matrix)
    for _, _, mp, _, _, _ in matrix:
        _require_devices(mp)
    model = _build_model()
    L = model.config.num_layers
    programs = []

    def check_knobs(engine, kv, samp=False):
        # serve-time env overrides win over ctor args by design — but
        # a leaked PADDLE_SERVE_KV_DTYPE/PADDLE_SERVE_WEIGHT_DTYPE
        # (or PADDLE_SERVE_SAMPLING) would silently harvest (and
        # baseline) a quantized/sampling program under the wrong
        # config label, or feed wrong-shaped step args to the
        # signature. Fail loudly instead.
        if (engine.kv_dtype, engine.weight_dtype) != (kv, kv):
            raise RuntimeError(
                f"harvest config kv={kv!r} resolved kv_dtype="
                f"{engine.kv_dtype!r}/weight_dtype="
                f"{engine.weight_dtype!r} (is PADDLE_SERVE_KV_DTYPE "
                "or PADDLE_SERVE_WEIGHT_DTYPE set?) — unset them to "
                "harvest")
        if engine.sampling != samp:
            raise RuntimeError(
                f"harvest config sampling={samp!r} resolved "
                f"{engine.sampling!r} (is PADDLE_SERVE_SAMPLING "
                "set?) — unset it to harvest")
        return engine

    def samp_rows(n):
        """The four traced sampling rows of an n-slot dispatch —
        the engine's host-arg layout, reproduced exactly."""
        return (jnp.asarray(np.zeros(n, np.float32)),
                jnp.asarray(np.zeros(n, np.int32)),
                jnp.asarray(np.ones(n, np.float32)),
                jnp.asarray(np.zeros((n, 2), np.uint32)))

    registry = None
    for backend, K, mp, kv, lora, samp in matrix:
        tag = (",int8" if kv else "") + (",lora" if lora else "") \
            + (",sampling" if samp else "")
        config = f"{backend},K={K},mp={mp}{tag}"
        quant = dict(kv_dtype=kv, weight_dtype=kv) if kv else {}
        if lora and registry is None:
            registry = _build_registry(model.config)
        adapt = dict(adapters=registry) if lora else {}
        skw = dict(sampling=True) if samp else {}
        eng = check_knobs(GenerationEngine(
            model, num_slots=TINY["slots"],
            block_size=TINY["block_size"], attention_backend=backend,
            spec_decode_k=K, mp_degree=mp, donate=True, **quant,
            **adapt, **skw), kv, samp)
        S, MB, C = eng.num_slots, eng.max_blocks, eng.prefill_chunk
        state = eng._state_arrays()
        kp, vp = eng.cache.kpool, eng.cache.vpool
        sc = (eng.cache.scales,) if kv else ()
        # adapter serving: the pool-array tuple rides before the host
        # args and the per-slot page row is the LAST host arg — the
        # engine's _dispatch_step layout, reproduced exactly
        lp = (eng.adapter_pool.arrays(),) if lora else ()
        arow = (jnp.asarray(np.zeros(S, np.int32)),) if lora else ()
        # probabilistic serving: the temp/top-k/top-p + key rows ride
        # between the tables and the adapter page row
        srows = samp_rows(S) if samp else ()
        tokens = jnp.asarray(np.zeros((S, K + 1), np.int32))
        positions = jnp.asarray(np.zeros(S, np.int32))
        tables = jnp.asarray(np.zeros((S, MB), np.int32))
        if K > 0:
            dlens = jnp.asarray(np.zeros(S, np.int32))
            step_args = (state, kp, vp, *sc, *lp, tokens, positions,
                         dlens, tables, *srows, *arow)
            step_name = "engine_verify_step"
        else:
            step_args = (state, kp, vp, *sc, *lp, tokens, positions,
                         tables, *srows, *arow)
            step_name = "engine_decode_step"
        programs.append(_trace_one(
            step_name, config, eng._decode_pure, eng._decode,
            step_args, mp, L,
            declared=_declared_specs(eng, step_args, kv, lora,
                                     eng._decode_n_out),
            geometry=_geometry(eng, L, S * (K + 1))))
        # the prefill programs and the COW copy are backend- and
        # K-invariant today (paged_prefill_chunk has no backend seam;
        # the decode/verify steps are where the backends diverge), so
        # they harvest ONCE per (mp, kv_dtype, lora) — if a prefill
        # backend ever grows, widen this to the full config string.
        # The COW copy is adapter-oblivious, so the lora configs skip
        # it (no duplicate baseline entry).
        if K == 0 and backend == "dense":
            arow1 = (jnp.asarray(np.zeros(1, np.int32)),) if lora \
                else ()
            srows1 = samp_rows(1) if samp else ()
            chunk_tokens = jnp.asarray(np.zeros((1, C), np.int32))
            row = jnp.asarray(np.zeros(MB, np.int32))
            pc_args = (state, kp, vp, *sc, *lp, chunk_tokens,
                       jnp.int32(0), jnp.int32(TINY["block_size"] + 1),
                       row, *srows1, *arow1)
            programs.append(_trace_one(
                "engine_prefill_chunk", f"mp={mp}{tag}",
                eng._prefill_pure, eng._prefill, pc_args, mp, L,
                declared=_declared_specs(eng, pc_args, kv, lora, 1),
                geometry=_geometry(eng, L, C)))
            bucket = TINY["seq"] // 2
            beng = check_knobs(GenerationEngine(
                model, num_slots=TINY["slots"],
                block_size=TINY["block_size"],
                attention_backend=backend,
                prefill_buckets=(bucket, TINY["seq"]), mp_degree=mp,
                donate=True, **quant, **adapt, **skw), kv, samp)
            btok = jnp.asarray(np.zeros((1, bucket), np.int32))
            # every arg from the BUCKETED engine itself — if its
            # geometry/state layout ever diverges from the chunked
            # engine's, the harvested signature must follow the real
            # program, not a lookalike
            bsc = (beng.cache.scales,) if kv else ()
            blp = (beng.adapter_pool.arrays(),) if lora else ()
            brow = jnp.asarray(np.zeros(beng.max_blocks, np.int32))
            bp_args = (beng._state_arrays(), beng.cache.kpool,
                       beng.cache.vpool, *bsc, *blp, btok,
                       jnp.int32(bucket - 2), brow, *srows1, *arow1)
            programs.append(_trace_one(
                "engine_prefill", f"mp={mp}{tag}", beng._prefill_pure,
                beng._prefill, bp_args, mp, L,
                declared=_declared_specs(beng, bp_args, kv, lora, 1),
                geometry=_geometry(beng, L, bucket)))
            if not lora and not samp:
                # the COW copy is adapter- AND sampling-oblivious:
                # both config families skip it (no duplicate entry)
                cow_args = (kp, vp, jnp.int32(1), jnp.int32(2), *sc)
                if mp > 1:
                    # plain jit, not shard_map — but the pools ride
                    # committed at pool_pspec() and the jit pins its
                    # out_shardings, so the declared truth is the same
                    pool = tuple(eng.cache.pool_pspec())
                    tail = (((),) if kv else ())
                    cow_declared = ((pool, pool, None, None) + tail,
                                    (pool, pool) + tail)
                else:
                    cow_declared = (None, None)
                programs.append(_trace_one(
                    "engine_cow_copy", f"mp={mp}{tag}", eng._cow_pure,
                    eng._cow, cow_args, mp, L,
                    declared=cow_declared,
                    geometry=_geometry(eng, L, 0)))
    if include_conv:
        programs.extend(_conv_programs())
    return programs


def _conv_programs():
    """The fused Pallas conv suite's programs (ops/pallas/conv.py):
    one tiny-but-real jitted instance per kernel family x stride,
    interpret-mode on CPU like the pallas attention configs. Not part
    of the engine matrix — they ride the DEFAULT harvest only, so a
    test harvesting a restricted engine matrix sees exactly what it
    asked for."""
    from paddle_tpu.ops.pallas import conv as pallas_conv

    return [_trace_one(name, config, pure, jitted, args, 1, 1)
            for name, config, pure, jitted, args
            in pallas_conv.harvest_programs()]


# ---------------------------------------------------------------------------
# drift snapshot (TRACE_BASELINE.json / TPU100)
# ---------------------------------------------------------------------------

def snapshot_of(programs):
    """program key -> per-step op/collective/byte counts, the unit of
    the committed drift baseline."""
    out = {}
    for p in programs:
        out[p.key] = {
            "ops": {k: p.ops[k] for k in sorted(p.ops)},
            "collectives": dict(sorted(p.collectives.items())),
            "const_bytes": p.const_bytes,
            "donated_aliases":
                p.lowered_text.count("tf.aliasing_output"),
        }
    return out


def load_trace_baseline(path):
    with open(path) as f:
        data = json.load(f)
    return data.get("programs", data)


def write_trace_baseline(path, programs):
    with open(path, "w") as f:
        json.dump({"version": 1, "programs": snapshot_of(programs)},
                  f, indent=1, sort_keys=True)
        f.write("\n")
    return len(programs)


def _diff_counts(cur, base):
    """Short human summary of what drifted."""
    bits = []
    for field in ("const_bytes", "donated_aliases"):
        if cur[field] != base.get(field):
            bits.append(f"{field} {base.get(field)} -> {cur[field]}")
    for field in ("collectives", "ops"):
        c, b = cur[field], base.get(field, {})
        for k in sorted(set(c) | set(b)):
            if c.get(k, 0) != b.get(k, 0):
                bits.append(f"{k} {b.get(k, 0)} -> {c.get(k, 0)}")
    return "; ".join(bits[:6]) + (" ..." if len(bits) > 6 else "")


def compare_snapshot(programs, baseline):
    """-> (drift findings [TPU100], stale baseline keys). Exact-match
    comparison: ANY change in a program's op/collective/byte counts
    fails loudly until --write-trace-baseline re-snapshots it and the
    diff is reviewed."""
    current = snapshot_of(programs)
    by_key = {p.key: p for p in programs}
    findings = []
    for key in sorted(current):
        prog = by_key[key]
        if key not in baseline:
            findings.append(Finding(
                rule="TPU100", path=prog.contract.declared_at, line=1,
                col=0, qualname=prog.contract.name, source=prog.config,
                message=f"program {key} has no TRACE_BASELINE.json "
                        "entry — run tools/tpu_verify.py "
                        "--write-trace-baseline and review the "
                        "snapshot"))
        elif current[key] != baseline[key]:
            findings.append(Finding(
                rule="TPU100", path=prog.contract.declared_at, line=1,
                col=0, qualname=prog.contract.name, source=prog.config,
                message=f"program {key} drifted from "
                        "TRACE_BASELINE.json: "
                        f"{_diff_counts(current[key], baseline[key])}"
                        " — intentional? re-snapshot with "
                        "--write-trace-baseline"))
    stale = sorted(set(baseline) - set(current))
    return findings, stale


# ---------------------------------------------------------------------------
# the full check
# ---------------------------------------------------------------------------

class TraceResult:
    """Mirror of analysis.Result for the trace tier."""

    def __init__(self):
        self.findings = []
        self.programs = []
        self.stale_baseline = []        # findings-baseline ids
        self.stale_trace_baseline = []  # snapshot keys

    def new_findings(self):
        return [f for f in self.findings
                if not f.suppressed and not f.baselined]

    def per_rule_counts(self):
        from .rules import all_trace_rule_ids

        out = {r: 0 for r in all_trace_rule_ids()}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def apply_findings_baseline(res, baseline):
    """Apply a findings baseline to a TraceResult — EXCEPT TPU100:
    a drift finding's stable ID hashes the program key, not the drift
    content, so one grandfathered entry would silently mask every
    FUTURE drift of that program too. Drift has its own reviewed
    acceptance mechanism (--write-trace-baseline); a baseline entry
    matching a TPU100 id is surfaced as stale instead of honored."""
    from ..baseline import apply_baseline

    return apply_baseline(
        [f for f in res.findings if f.rule != "TPU100"], baseline)


def verify_matrix(matrix=None, baseline=None, trace_baseline="auto"):
    """Harvest the matrix and run every rule + the drift comparison.

    `baseline` is a loaded findings baseline ({id: entry}, see
    analysis.baseline) or None; `trace_baseline` is a path, a loaded
    snapshot dict, "auto" (the committed TRACE_BASELINE.json when
    present) or None to skip drift checking."""
    res = TraceResult()
    res.programs = harvest(matrix)
    for prog in res.programs:
        res.findings.extend(check_program(prog))
    if trace_baseline == "auto":
        trace_baseline = DEFAULT_TRACE_BASELINE \
            if os.path.exists(DEFAULT_TRACE_BASELINE) else None
    if isinstance(trace_baseline, str):
        trace_baseline = load_trace_baseline(trace_baseline)
    if trace_baseline is not None:
        drift, res.stale_trace_baseline = compare_snapshot(
            res.programs, trace_baseline)
        res.findings.extend(drift)
    assign_ids(res.findings)
    if baseline:
        res.stale_baseline = apply_findings_baseline(res, baseline)
    res.findings.sort(key=lambda f: (f.path, f.qualname, f.source,
                                     f.rule))
    return res
