"""TPU1xx rules — contract checks over TRACED programs.

tpu-lint's TPU0xx family reads python source; this family reads what
tracing PRODUCES: the jaxpr and the lowered StableHLO module of every
registered compiled program, harvested abstractly on CPU (no device
execution). Each rule takes a `TracedProgram` record and returns
`analysis.findings.Finding`s anchored at the contract's declaration
site — the step builder, not the checker.

No rule imports jax: jaxprs are walked by duck typing (`.eqns`,
`.primitive.name`, `.params`) and dtypes compared by name, so the
module imports clean in pre-device CI stages (the import-smoke
contract shared with `paddle_tpu.analysis`).
"""
from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from functools import cached_property

from ..findings import Finding
from .contracts import resolve_budget

#: Mesh-collective primitive names TPU104 classifies and counts.
COLLECTIVE_PRIMS = frozenset({
    "all_gather", "psum", "psum2", "all_to_all", "ppermute",
    "pbroadcast", "reduce_scatter", "psum_scatter", "pmin", "pmax",
    "pgather",
})

#: Host-callback primitives TPU106 bans from compiled steps.
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "outside_call",
    "host_callback_call",
})

#: Contraction / add-reduction primitives whose accumulator dtype
#: follows the operand dtype unless pinned (TPU103).
_ACCUM_PRIMS = ("dot_general", "reduce_sum")

#: Floating dtypes narrower than fp32 — accumulating IN them is the
#: bf16 cancellation bug class (DESIGN_DECISIONS, paged-attention PV
#: fix).
_NARROW_FLOATS = ("bfloat16", "float16", "float8_e4m3fn",
                  "float8_e5m2")

#: Narrow integer dtypes the quantized serving paths contract over
#: (int8 KV / int8 weights): an int8 dot_general must accumulate in
#: fp32 (the contract's accum_dtype) or int32 — accumulating in a
#: narrow float (int8 -> bf16) or staying int8 loses exactly the bits
#: quantization already spent.
_NARROW_INTS = ("int8", "uint8", "int4", "uint4")

_WIDE_FLOATS = ("float32", "float64")

#: Acceptable accumulators for narrow-INT operands: wide floats plus
#: the standard exact integer accumulators.
_WIDE_INT_ACCUMS = _WIDE_FLOATS + ("int32", "int64")


@dataclass
class TracedProgram:
    """One harvested (program, config) pair — everything the rules
    need, captured once so each rule stays a pure function."""

    contract: object                # TraceContract
    config: str                     # e.g. "dense,K=4,mp=2"
    mp: int
    num_layers: int
    jaxpr: object                   # ClosedJaxpr
    lowered_text: str               # StableHLO module text
    donated_leaves: int             # array leaves under donate_argnums
    arg_leaves: list = field(default_factory=list)  # (path, leaf)
    # declared layout truth, captured by the harvester from the
    # ENGINE'S OWN spec surfaces (_tp_specs / pool_pspec() /
    # adapter pool_pspecs()) for the tpu-shard tier (TPU302/TPU303):
    # per argument leaf (in signature order) a tuple of per-dim mesh
    # axis names (None = unsharded dim), () = declared replicated,
    # or None = no declared layout (host args); None for the whole
    # field at mp == 1 / non-engine programs. Pure data — no jax
    # objects, so the rules stay import-smoke clean.
    declared_in_specs: tuple = None
    declared_out_specs: tuple = None
    # serving geometry symbols (tokens/hidden/intermediate/vocab/
    # layers/blocks/block_size/heads/head_dim/slots) the tpu-shard
    # payload bounds evaluate over; None for non-engine programs
    geometry: dict = None

    @property
    def key(self):
        return f"{self.contract.name}[{self.config}]"

    # each full jaxpr walk is O(program); rules, the drift snapshot
    # and --stats all consume the same aggregates, so walk ONCE and
    # cache on the record
    @cached_property
    def ops(self):
        return op_counts(self.jaxpr)

    @property
    def collectives(self):
        return {k: v for k, v in self.ops.items()
                if k in COLLECTIVE_PRIMS}

    @cached_property
    def consts(self):
        return const_entries(self.jaxpr)

    @property
    def const_bytes(self):
        return sum(n for _, _, n in self.consts)


# ---------------------------------------------------------------------------
# jaxpr walking (duck-typed: no jax import)
# ---------------------------------------------------------------------------

def _inner_jaxpr(obj):
    """Jaxpr carried by `obj` (a Jaxpr, a ClosedJaxpr, or neither)."""
    if hasattr(obj, "eqns"):
        return obj
    inner = getattr(obj, "jaxpr", None)
    return inner if hasattr(inner, "eqns") else None


def iter_eqns(jaxpr):
    """Every equation in `jaxpr` and (recursively) in any sub-jaxpr
    its equations carry as params — scan/while/cond bodies, pallas
    kernels, shard_map bodies. Loop bodies are counted ONCE (static
    program text, not trip-count-weighted)."""
    top = _inner_jaxpr(jaxpr)
    if top is None:
        return
    for eqn in top.eqns:
        yield eqn
        for v in eqn.params.values():
            vs = v if isinstance(v, (list, tuple)) else (v,)
            for sub in vs:
                inner = _inner_jaxpr(sub)
                if inner is not None:
                    yield from iter_eqns(inner)


def op_counts(jaxpr):
    """primitive name -> static occurrence count, recursive."""
    return Counter(e.primitive.name for e in iter_eqns(jaxpr))


def collective_counts(jaxpr):
    return {k: v for k, v in op_counts(jaxpr).items()
            if k in COLLECTIVE_PRIMS}


def const_entries(jaxpr):
    """(shape, dtype, nbytes) for every constant closed over by the
    program, including sub-jaxpr consts."""
    out = []
    seen = set()

    def visit(closed):
        if id(closed) in seen:
            return
        seen.add(id(closed))
        for c in getattr(closed, "consts", ()) or ():
            if hasattr(c, "nbytes"):
                out.append((tuple(getattr(c, "shape", ())),
                            str(getattr(c, "dtype", "?")),
                            int(c.nbytes)))
        inner = _inner_jaxpr(closed)
        if inner is None:
            return
        for eqn in inner.eqns:
            for v in eqn.params.values():
                vs = v if isinstance(v, (list, tuple)) else (v,)
                for sub in vs:
                    if hasattr(sub, "consts") or \
                            _inner_jaxpr(sub) is not None:
                        visit(sub)

    visit(jaxpr)
    return out


def total_const_bytes(jaxpr):
    return sum(n for _, _, n in const_entries(jaxpr))


def _dtype_name(aval):
    return str(getattr(aval, "dtype", "?"))


def _is_weak(leaf):
    aval = getattr(leaf, "aval", leaf)
    return bool(getattr(aval, "weak_type", False))


def _finding(rule, prog, message):
    return Finding(rule=rule, path=prog.contract.declared_at, line=1,
                   col=0, message=message,
                   qualname=prog.contract.name, source=prog.config)


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------

def check_tpu101(prog):
    """TPU101 donation-actually-applied: every array leaf under the
    declared donate_argnums must appear as a PINNED input/output alias
    (`tf.aliasing_output`) in the lowered module. A `jax.buffer_donor`
    marker is NOT enough — it is a free hint XLA may ignore, so the
    paged pools could silently double their HBM footprint; a dropped
    alias (output shape/dtype/sharding mismatch) is exactly the silent
    regression this rule exists to catch."""
    if not prog.contract.donate_argnums:
        return []
    pinned = prog.lowered_text.count("tf.aliasing_output")
    donor = prog.lowered_text.count("jax.buffer_donor")
    if pinned >= prog.donated_leaves:
        return []
    return [_finding(
        "TPU101", prog,
        f"declared donate_argnums="
        f"{tuple(prog.contract.donate_argnums)} must pin "
        f"{prog.donated_leaves} input/output aliases in the lowered "
        f"module, found {pinned} (best-effort jax.buffer_donor "
        f"markers: {donor}) — donation was dropped or demoted; the "
        "donated buffers will be copied, not updated in place")]


def check_tpu102(prog):
    """TPU102 baked-large-constant: weights/tables captured by closure
    are embedded in the program as literals — every retrace re-uploads
    them and the compiled binary carries them forever. State must ride
    as traced arguments (the TrainStep idiom)."""
    cap = prog.contract.max_const_bytes
    out = []
    for shape, dtype, nbytes in prog.consts:
        if nbytes > cap:
            out.append(_finding(
                "TPU102", prog,
                f"constant {dtype}{list(shape)} ({nbytes} bytes) baked "
                f"into the jaxpr exceeds max_const_bytes={cap} — "
                "thread it through the program arguments instead of "
                "closing over it"))
    return out


def check_tpu103(prog):
    """TPU103 accumulation-dtype: a contraction (dot_general) or add-
    reduction over sub-fp32 operands must accumulate at
    `contract.accum_dtype` or wider (`preferred_element_type`) — bf16
    accumulation silently cancels low-order bits (the PV-accumulation
    bug class). Narrow-INT operands (the int8 quantized-serving
    paths) must accumulate in a wide float or an exact int32/int64:
    int8 operands with fp32 accumulation pass, int8 -> bf16 (or a
    dot that stays int8) fires."""
    if prog.contract.accum_dtype not in _WIDE_FLOATS:
        raise ValueError(
            f"contract {prog.contract.name}: accum_dtype must be one "
            f"of {_WIDE_FLOATS}")
    out = []
    counted = Counter()
    for eqn in iter_eqns(prog.jaxpr):
        name = eqn.primitive.name
        if name not in _ACCUM_PRIMS:
            continue
        in_dts = [_dtype_name(v.aval) for v in eqn.invars
                  if hasattr(v, "aval")]
        narrow_int = any(d in _NARROW_INTS for d in in_dts)
        if not narrow_int \
                and not any(d in _NARROW_FLOATS for d in in_dts):
            continue
        out_dt = _dtype_name(eqn.outvars[0].aval)
        if out_dt in (_WIDE_INT_ACCUMS if narrow_int
                      else _WIDE_FLOATS):
            continue
        counted[(name, tuple(in_dts), out_dt)] += 1
    for (name, in_dts, out_dt), n in sorted(counted.items()):
        out.append(_finding(
            "TPU103", prog,
            f"{name} over {'/'.join(in_dts)} accumulates in {out_dt} "
            f"({n} occurrence(s)) — pin preferred_element_type="
            f"{prog.contract.accum_dtype} (accumulate wide, cast "
            "once)"))
    return out


def check_tpu104(prog):
    """TPU104 collective-budget: classify and count every mesh
    collective in the step's jaxpr (recursively — shard_map bodies
    included) against the contract's declared per-layer budget. An
    unsharded (mp == 1) step is allowed NO collectives; a sharded step
    gets `per_layer * num_layers + fixed` per kind. One accidental
    extra all-gather in the decode path fails here instead of
    stretching every serving iteration."""
    actual = prog.collectives
    budget = resolve_budget(prog.contract) if prog.mp > 1 else None
    out = []
    kinds = set(actual)
    if budget is not None:
        kinds |= set(budget.kinds())
    for kind in sorted(kinds):
        n = actual.get(kind, 0)
        allowed = budget.allowed(kind, prog.num_layers) \
            if budget is not None else 0
        if n > allowed:
            if budget is not None:
                detail = (f"budget {allowed} = "
                          f"{dict(budget.per_layer).get(kind, 0)}"
                          f"/layer x {prog.num_layers} layers + "
                          f"{dict(budget.fixed).get(kind, 0)} fixed")
            elif prog.mp > 1:
                detail = ("this step's contract declares no "
                          "collective budget — none allowed at any "
                          "mp")
            else:
                detail = "unsharded steps run no collectives"
            out.append(_finding(
                "TPU104", prog,
                f"{kind} appears {n}x in the compiled step, allowed "
                f"{allowed} ({detail})"))
    return out


def check_tpu105(prog):
    """TPU105 trace-key instability: a python scalar (or weak-typed
    array) in a program's signature makes the jit cache key depend on
    promotion context — two call sites that agree on values can still
    retrace. Engine dispatch must pass strong-typed arrays
    (`jnp.int32(x)`, `jnp.asarray(np_arr)`), never bare python
    numbers.

    Boundary, stated plainly (the r9 etiquette): over the harvest
    matrix this rule inspects the HARVESTED example args, which
    mirror — but are not — the host scheduler's live dispatch; a
    weak-typed leaf introduced only at a real dispatch site is caught
    by the runtime `decode_traces == 1` probes (a per-value retrace
    fails those gates loudly), while this rule pins the hazard class
    itself via fixtures and guards every signature the harvester
    feeds."""
    out = []
    for path, leaf in prog.arg_leaves:
        if isinstance(leaf, (bool, int, float)):
            out.append(_finding(
                "TPU105", prog,
                f"python {type(leaf).__name__} at arg {path} enters "
                "the traced signature — pass a strong-typed array "
                "(jnp.int32/asarray) so the trace-cache key is "
                "stable"))
        elif _is_weak(leaf):
            out.append(_finding(
                "TPU105", prog,
                f"weak-typed leaf at arg {path} ({_dtype_name(getattr(leaf, 'aval', leaf))}) "
                "— a python scalar leaked into the signature; cast it "
                "explicitly"))
    return out


def check_tpu106(prog):
    """TPU106 host-callback-in-compiled-step: a callback primitive
    re-enters python mid-program — a host round-trip per dispatch on
    the serving hot path (and a tracing hazard under donation)."""
    if prog.contract.allow_host_callbacks:
        return []
    counts = prog.ops
    out = []
    for name in sorted(counts):
        if name in CALLBACK_PRIMS or "callback" in name:
            out.append(_finding(
                "TPU106", prog,
                f"host callback primitive `{name}` appears "
                f"{counts[name]}x in the compiled step — hot-path "
                "programs must not re-enter python"))
    return out


#: rule id -> (name, description, checker). TPU100 is the meta-rule
#: for TRACE_BASELINE drift (reported by the harvester, like
#: tpu-lint's TPU000 for unparseable files).
TRACE_RULES = {
    "TPU100": ("trace-drift",
               "per-step op/collective/byte counts drifted from the "
               "committed TRACE_BASELINE.json", None),
    "TPU101": ("donation-not-applied",
               "declared donate_argnums produced no pinned "
               "input/output alias in the lowered module",
               check_tpu101),
    "TPU102": ("baked-large-constant",
               "closure-captured array embedded in the jaxpr over the "
               "contract's size threshold", check_tpu102),
    "TPU103": ("accum-dtype",
               "contraction/reduction over sub-fp32 operands without "
               "fp32 accumulation", check_tpu103),
    "TPU104": ("collective-budget",
               "mesh collectives per compiled step exceed the "
               "declared per-layer budget", check_tpu104),
    "TPU105": ("trace-key-instability",
               "python-scalar / weak-typed leaf in the program "
               "signature", check_tpu105),
    "TPU106": ("host-callback-in-step",
               "host callback primitive inside a compiled hot-path "
               "program", check_tpu106),
}


def all_trace_rule_ids():
    return sorted(TRACE_RULES)


def check_program(prog):
    """Run every TPU1xx rule over one traced program. Contract waivers
    mark findings suppressed (inline-justified, colocated with the
    declaration) rather than dropping them — `--stats` still counts
    them, mirroring tpu-lint suppression semantics."""
    findings = []
    for rule_id in all_trace_rule_ids():
        check = TRACE_RULES[rule_id][2]
        if check is None:
            continue
        found = check(prog)
        why = prog.contract.waived(rule_id)
        if why is not None:
            for f in found:
                f.suppressed = True
        findings.extend(found)
    return findings
