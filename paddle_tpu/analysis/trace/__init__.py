"""tpu-verify — jaxpr/StableHLO trace-contract checking.

The second analysis tier: where tpu-lint (`paddle_tpu.analysis`, AST)
catches hazards in the python that tracing ERASES, this package
checks the properties only visible in what tracing PRODUCES — the
jaxpr and lowered StableHLO of every registered compiled engine
program (DESIGN_DECISIONS r9 drew exactly this boundary; r13 closes
it). `verify_matrix` is the in-process API the tier-1 gate uses;
`tools/tpu_verify.py` is the CLI.

LAZY package init (PEP 562), for the same reason as the parent
package: the engine/model/op modules import
`analysis.trace.contracts` (pure data) at module scope to declare
their contracts, so `import paddle_tpu` executes this file — the
checker itself (rules, harvester) loads only when verification runs.
A JAX backend is initialized only once `harvest()` is invoked, and
even then programs are traced/lowered abstractly, never executed.
"""
from __future__ import annotations

_EXPORTS = {
    "contracts": ("CollectiveBudget", "TraceContract", "get_contract",
                  "register_contract", "registered_contracts",
                  "resolve_budget"),
    "harvest": ("DEFAULT_TRACE_BASELINE", "TraceResult",
                "apply_findings_baseline", "compare_snapshot",
                "default_matrix", "harvest", "load_trace_baseline",
                "snapshot_of", "verify_matrix",
                "write_trace_baseline"),
    "rules": ("TRACE_RULES", "TracedProgram", "all_trace_rule_ids",
              "check_program", "collective_counts", "const_entries",
              "iter_eqns", "op_counts", "total_const_bytes"),
}

__all__ = sorted(n for names in _EXPORTS.values() for n in names)

_WHENCE = {name: mod for mod, names in _EXPORTS.items()
           for name in names}


def __getattr__(name):
    mod = _WHENCE.get(name)
    if mod is not None:
        import importlib

        return getattr(
            importlib.import_module(f".{mod}", __name__), name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_WHENCE))
