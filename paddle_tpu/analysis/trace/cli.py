"""tpu-verify CLI implementation (thin wrapper lives in
tools/tpu_verify.py), mirroring tpu_lint's interface.

Exit codes: 0 clean (against baselines), 1 findings, 2 usage/baseline
error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from ..baseline import BaselineError, load_baseline, write_baseline
from .harvest import DEFAULT_TRACE_BASELINE, _REPO_ROOT, \
    load_trace_baseline, verify_matrix, write_trace_baseline
from .rules import TRACE_RULES, all_trace_rule_ids

DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "tools",
                                "tpu_verify_baseline.json")


def _print_stats(res, out):
    counts = res.per_rule_counts()
    suppressed = sum(1 for f in res.findings if f.suppressed)
    baselined = sum(1 for f in res.findings if f.baselined)
    print("-- tpu-verify stats ----------------------------------",
          file=out)
    print(f"programs traced: {len(res.programs)}", file=out)
    for p in res.programs:
        print(f"  {p.key}: {sum(p.ops.values())} eqns, "
              f"collectives={p.collectives or '{}'}, "
              f"const_bytes={p.const_bytes}", file=out)
    for rule in all_trace_rule_ids():
        name = TRACE_RULES[rule][0]
        print(f"{rule} {name:<26} {counts.get(rule, 0)}", file=out)
    print(f"suppressed (contract waivers): {suppressed}   "
          f"baselined: {baselined}", file=out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tpu_verify",
        description="jaxpr/StableHLO trace-contract checker for every "
                    "compiled engine step")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--baseline", default=None,
                    help="findings baseline JSON ('none' disables; "
                         "default: tools/tpu_verify_baseline.json "
                         "when present)")
    ap.add_argument("--write-baseline", metavar="PATH",
                    help="write current new findings as a baseline "
                         "skeleton (justifications left empty on "
                         "purpose) and exit")
    ap.add_argument("--trace-baseline", default=None,
                    help="drift snapshot JSON ('none' disables; "
                         "default: TRACE_BASELINE.json at the repo "
                         "root when present)")
    ap.add_argument("--write-trace-baseline", nargs="?", metavar="PATH",
                    const=DEFAULT_TRACE_BASELINE,
                    help="re-snapshot per-program op/collective/byte "
                         "counts (default path: the committed "
                         "TRACE_BASELINE.json) and exit")
    ap.add_argument("--stats", action="store_true",
                    help="print per-program trace stats and per-rule "
                         "finding counts")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_trace_rule_ids():
            name, desc, _ = TRACE_RULES[rule]
            print(f"{rule}  {name:<26} {desc}")
        return 0

    baseline = {}
    if args.baseline != "none" and not args.write_baseline:
        bpath = args.baseline or (
            DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE)
            else None)
        if args.baseline and not os.path.exists(args.baseline):
            print(f"tpu_verify: baseline not found: {args.baseline}",
                  file=sys.stderr)
            return 2
        if bpath:
            try:
                baseline = load_baseline(bpath)
            except (BaselineError, json.JSONDecodeError) as e:
                print(f"tpu_verify: bad baseline {bpath}: {e}",
                      file=sys.stderr)
                return 2

    # resolve AND load the drift snapshot BEFORE the (expensive)
    # harvest: a corrupt file is a usage error (exit 2), not a
    # 15s-later traceback
    trace_baseline = None
    if not args.write_trace_baseline and args.trace_baseline != "none":
        tb_path = args.trace_baseline or (
            DEFAULT_TRACE_BASELINE
            if os.path.exists(DEFAULT_TRACE_BASELINE) else None)
        if args.trace_baseline and not os.path.exists(
                args.trace_baseline):
            print("tpu_verify: trace baseline not found: "
                  f"{args.trace_baseline}", file=sys.stderr)
            return 2
        if tb_path:
            try:
                trace_baseline = load_trace_baseline(tb_path)
            except (json.JSONDecodeError, OSError) as e:
                print(f"tpu_verify: bad trace baseline {tb_path}: {e}",
                      file=sys.stderr)
                return 2

    try:
        res = verify_matrix(baseline=baseline,
                            trace_baseline=trace_baseline)
    except RuntimeError as e:
        print(f"tpu_verify: {e}", file=sys.stderr)
        return 2

    if args.write_trace_baseline:
        n = write_trace_baseline(args.write_trace_baseline,
                                 res.programs)
        print(f"snapshotted {n} programs to "
              f"{args.write_trace_baseline} — review the diff before "
              "committing")
        return 0

    if args.write_baseline:
        # drift (TPU100) is excluded: its ID ignores the drift
        # content, so a baseline entry would mask all future drift of
        # that program — drift acceptance is --write-trace-baseline
        n = write_baseline(args.write_baseline,
                           [f for f in res.new_findings()
                            if f.rule != "TPU100"])
        print(f"wrote {n} entries to {args.write_baseline} — add a "
              "justification to each (the loader rejects empty ones; "
              "TPU100 drift is never grandfatherable)")
        return 0

    new = res.new_findings()
    if args.format == "json":
        doc = {
            "findings": [f.to_dict() for f in new],
            "suppressed": sum(1 for f in res.findings if f.suppressed),
            "baselined": sum(1 for f in res.findings if f.baselined),
            "stale_baseline": res.stale_baseline,
            "stale_trace_baseline": res.stale_trace_baseline,
            "programs": [p.key for p in res.programs],
        }
        print(json.dumps(doc, indent=1))
    else:
        for f in new:
            print(f.render())
        for bid in res.stale_baseline:
            print(f"note: stale baseline entry {bid} — no current "
                  "finding matches; remove it")
        for key in res.stale_trace_baseline:
            print(f"note: stale TRACE_BASELINE entry {key} — no "
                  "current program matches; re-snapshot")
        if not new:
            print(f"tpu-verify clean: {len(res.programs)} programs, "
                  f"{sum(1 for f in res.findings if f.baselined)} "
                  "baselined, "
                  f"{sum(1 for f in res.findings if f.suppressed)} "
                  "waived")
    if args.stats:
        _print_stats(res, sys.stdout)
    return 1 if new else 0
