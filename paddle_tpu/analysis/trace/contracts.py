"""TraceContract — a compiled program's declared trace-time contract.

This module is deliberately PURE DATA (no jax import, no framework
import): the modules that BUILD compiled programs (inference/engine.py,
models/gpt.py, ops/paged_attention.py) import it at module scope to
declare their contracts right next to the step builders, and importing
them must never pull analysis machinery — let alone a JAX backend —
into the process. The harvester (`analysis.trace.harvest`) imports the
builder modules lazily, which is what fills the registry.

A contract declares what must hold in the program AFTER tracing —
the properties tpu-lint's AST pass cannot see (DESIGN_DECISIONS r9's
false-negative boundary): donation really aliasing, no weights baked
as constants, fp32 accumulation on narrow-dtype contractions, a
bounded collective count per sharded step, strong-typed trace keys,
and no host callbacks. `analysis.trace.rules` enforces them per
harvested (program, config) pair.
"""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class CollectiveBudget:
    """Upper bound on mesh collectives per compiled step, split into a
    per-transformer-layer part and a fixed (embed / lm-head) part:
    allowed(kind) = per_layer[kind] * num_layers + fixed[kind]. A kind
    absent from both maps is allowed zero times — an accidental extra
    all-gather (or a brand-new reduce-scatter) in a sharded step fails
    TPU104 instead of silently stretching every decode iteration."""

    per_layer: tuple = ()        # (("all_gather", 4), ...)
    fixed: tuple = ()            # (("all_gather", 1), ("psum", 1), ...)

    def allowed(self, kind, num_layers):
        per = dict(self.per_layer).get(kind, 0)
        fix = dict(self.fixed).get(kind, 0)
        return per * num_layers + fix

    def kinds(self):
        return sorted(set(dict(self.per_layer)) | set(dict(self.fixed)))


@dataclass(frozen=True)
class TraceContract:
    """Declared trace-time contract for ONE compiled program.

    name: the program's `__name__` (the engine's step-body names);
        doubles as the key into `introspect.ENGINE_STEP_DONATION`.
    declared_at: repo-relative path of the module declaring this
        contract — findings anchor there, so a TPU1xx failure points
        at the step builder, not the checker.
    donate_argnums: positional args whose buffers the program donates;
        TPU101 requires one pinned input/output alias per donated
        array leaf in the lowered module.
    collective_budget: CollectiveBudget for the program's SHARDED
        (mp > 1) lowering, or a lazy "pkg.mod:NAME" reference resolved
        at harvest time (keeps this declaration colocated with the
        engine while the budget itself lives next to the collective-
        emitting model code). At mp == 1 every program's budget is
        zero collectives regardless of this field.
    max_const_bytes: TPU102 threshold — any single constant baked into
        the jaxpr above this size fails (weights/tables must ride as
        traced arguments, never closure captures).
    accum_dtype: minimum accumulation width TPU103 demands of
        contractions (dot_general) and add-reductions over
        sub-fp32 operands.
    allow_host_callbacks: TPU106 — compiled hot-path steps must never
        re-enter python mid-program.
    per_token: the program runs once PER GENERATED TOKEN (the decode /
        verify steps — the host loop body), so every collective in it
        sits on the per-token latency path. tpu-shard TPU305 flags
        per-token collectives that cross a budget axis declared "dcn"
        (slow inter-slice link); prefills and the COW copy run per
        admission, not per token, and leave this False.
    waive: ((rule_id, justification), ...) — inline, colocated
        suppressions. Empty justifications are rejected at check time,
        same etiquette as the committed baseline.
    """

    name: str
    declared_at: str
    donate_argnums: tuple = ()
    collective_budget: object = None      # CollectiveBudget | "mod:NAME"
    max_const_bytes: int = 4096
    accum_dtype: str = "float32"
    allow_host_callbacks: bool = False
    per_token: bool = False
    waive: tuple = ()

    def waived(self, rule_id):
        """Justification string when rule_id is waived, else None.
        An empty justification is a declaration error, not a waiver."""
        for rid, why in self.waive:
            if rid == rule_id:
                if not str(why).strip():
                    raise ValueError(
                        f"contract {self.name} waives {rid} without a "
                        "justification — write the reason or fix it")
                return why
        return None


#: name -> TraceContract, filled by the builder modules' import-time
#: declarations (engine steps, the COW block copy).
_REGISTRY = {}


def register_contract(contract):
    """Publish a contract (idempotent re-registration with identical
    content is fine — modules may be reimported; a CONFLICTING
    redeclaration is a bug and raises)."""
    prev = _REGISTRY.get(contract.name)
    if prev is not None and prev != contract:
        raise ValueError(
            f"conflicting TraceContract redeclaration for "
            f"{contract.name!r}")
    _REGISTRY[contract.name] = contract
    return contract


def get_contract(name):
    c = _REGISTRY.get(name)
    if c is None:
        raise KeyError(
            f"no TraceContract registered under {name!r} — declare it "
            "next to the step builder (see inference/engine.py)")
    return c


def registered_contracts():
    return dict(_REGISTRY)


def resolve_budget(contract):
    """Resolve a contract's collective budget, following a lazy
    "pkg.mod:NAME" reference (the colocation seam: the engine declares
    WHICH budget applies, the model module owns WHAT it is)."""
    budget = contract.collective_budget
    if isinstance(budget, str):
        import importlib

        mod_name, _, attr = budget.partition(":")
        try:
            budget = getattr(importlib.import_module(mod_name), attr)
        except (ImportError, AttributeError) as e:
            raise ValueError(
                f"contract {contract.name} (declared at "
                f"{contract.declared_at}) references collective "
                f"budget {contract.collective_budget!r} which does "
                f"not resolve: {e}") from e
    if budget is not None and not isinstance(budget, CollectiveBudget):
        # the per-axis table (jit.introspect.AxisCollectiveBudget)
        # exposes the same count surface (per_layer/fixed/allowed/
        # kinds) PLUS the axis/byte view tpu-shard consumes — both
        # tiers resolve through here so the tables cannot fork
        from paddle_tpu.jit.introspect import AxisCollectiveBudget

        if not isinstance(budget, AxisCollectiveBudget):
            raise TypeError(
                f"contract {contract.name}: collective_budget must be "
                "a CollectiveBudget, an AxisCollectiveBudget or a "
                f"'mod:NAME' reference, got {budget!r}")
    return budget
