"""tpu-lint / tpu-verify — static & trace analysis of this codebase.

LAZY package init (PEP 562): the serving modules
(`inference/engine.py`, `models/gpt.py`, `ops/paged_attention.py`)
import `paddle_tpu.analysis.trace.contracts` at module scope to
declare their trace contracts, which executes this file on every
`import paddle_tpu` — so nothing heavier than this forwarding table
may run here. The actual analyzer (AST engine, rules, findings,
baseline) lives in `analysis.core` and loads only when an analysis
entry point is first touched; a bug in analyzer-only code can never
break importing the framework.
"""
from __future__ import annotations

__all__ = ["analyze_file", "analyze_paths", "collect_files", "Finding",
           "Result", "RULES", "all_rule_ids", "load_baseline",
           "apply_baseline", "write_baseline", "BaselineError"]

#: Names forwarded from analysis.core on first access (the public API
#: plus the private root anchor the CLI shares).
_CORE_NAMES = set(__all__) | {"_REPO_ROOT"}


def __getattr__(name):
    if name in _CORE_NAMES:
        from . import core

        return getattr(core, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | _CORE_NAMES)
