"""TPU3xx rules — sharding-layout & collective-byte checks over
harvested programs.

Each rule is a pure function over a `model.ShardRecord` returning
`analysis.findings.Finding`s anchored at the contract's declaration
site (the step builder, same convention as the trace tier). TPU300 is
the meta-rule for SHARD_BASELINE.json drift, reported by
`core.compare_snapshot` like tpu-verify's TPU100.

No jax import (the import-smoke contract): everything a rule reads
was extracted by `model` from the jaxpr walk, the lowered text and
the declared spec tuples the harvester captured.
"""
from __future__ import annotations

from ..findings import Finding
from .model import LARGE_BUFFER_BYTES, eval_payload


def _finding(rule, rec, message):
    return Finding(rule=rule, path=rec.contract.declared_at, line=1,
                   col=0, message=message,
                   qualname=rec.contract.name, source=rec.prog.config)


def _fmt_spec(spec):
    if spec == ():
        return "replicated"
    return "P(" + ", ".join("None" if a is None else repr(a)
                            for a in spec) + ")"


def _fmt_counts(counts):
    if counts is None:
        return "unspecified"
    if counts == ():
        return "replicated"
    return "split " + "x".join(str(c) for c in counts)


def _max_bound(rec, axis, kind):
    """Largest declared payload bound (bytes) for (axis, kind), or
    None when the kind is undeclared on that axis."""
    bounds = rec.axis_budget.payload_bounds(axis, kind)
    if not bounds or rec.prog.geometry is None:
        return None
    return max(eval_payload(b, rec.prog.geometry) for b in bounds)


def check_tpu301(rec):
    """TPU301 undeclared-resharding: every collective must cross an
    axis the budget DECLARES, at a declared kind, within the declared
    count, and the per-axis moved-byte total must stay under the
    budget-derived cap ((per_layer x layers + fixed) x payload bound x
    (axis_size - 1)). An all-gather over an axis the table never
    mentions — or mp-axis traffic growing past what the declared
    payloads can account for — is a resharding nobody signed off on,
    the silent DCN-saturating surprise class."""
    if not rec.sites:
        return []
    out = []
    budget = rec.axis_budget
    if budget is None:
        kinds = sorted({s.kind for s in rec.sites})
        return [_finding(
            "TPU301", rec,
            f"program runs {', '.join(kinds)} but its contract "
            "declares no per-axis collective budget "
            "(AxisCollectiveBudget) — every collective is an "
            "undeclared resharding")]
    declared = set(budget.axis_names())
    L = rec.prog.num_layers
    for axis in sorted(rec.axis_totals):
        per_kind = rec.axis_totals[axis]
        if axis not in declared:
            kinds = ", ".join(f"{k} x{v['count']}"
                              for k, v in sorted(per_kind.items()))
            out.append(_finding(
                "TPU301", rec,
                f"collectives cross mesh axis '{axis}' which the "
                f"budget does not declare ({kinds}) — undeclared "
                "resharding"))
            continue
        size = int(rec.axis_sizes.get(axis, 1))
        for kind in sorted(per_kind):
            n = per_kind[kind]["count"]
            moved = per_kind[kind]["moved_bytes"]
            allowed = budget.allowed_on_axis(axis, kind, L)
            if n > allowed:
                out.append(_finding(
                    "TPU301", rec,
                    f"{kind} crosses axis '{axis}' {n}x "
                    f"({moved} bytes moved), allowed {allowed} — "
                    "an undeclared resharding joined the step"))
                continue
            bound = _max_bound(rec, axis, kind)
            if bound is not None:
                cap = allowed * bound * max(size - 1, 1)
                if moved > cap:
                    out.append(_finding(
                        "TPU301", rec,
                        f"{kind} traffic over axis '{axis}' moves "
                        f"{moved} bytes, budget caps "
                        f"{cap} (= {allowed} x {bound}-byte payload "
                        f"bound x {max(size - 1, 1)} peers) — the "
                        "payloads outgrew the declared layout"))
    return out


def check_tpu302(rec):
    """TPU302 replicated-large-buffer: a signature leaf above
    LARGE_BUFFER_BYTES that the declared layout truth (pool_pspec /
    _tp_specs / adapter pool_pspecs) says SHARDED but that lowered
    replicated (or with no sharding at all) — the exact drift class
    TPU101 caught for donation: the buffer silently costs
    axis_size x its HBM share on every chip."""
    if not rec.sharded:
        return []
    out = []
    for side, i, spec, counts, nbytes in rec.declared_vs_lowered():
        if not any(a is not None for a in spec):
            continue                      # declared replicated
        if counts not in ((), None) or nbytes < LARGE_BUFFER_BYTES:
            continue
        out.append(_finding(
            "TPU302", rec,
            f"{side}put leaf #{i} ({nbytes} bytes) is declared "
            f"{_fmt_spec(spec)} but lowered "
            f"{_fmt_counts(counts)} — a sharded buffer silently "
            "replicated onto every chip"))
    return out


def check_tpu303(rec):
    """TPU303 pspec-layout drift: any declared-layout leaf (donated
    pool, scale grid, adapter page array, weight leaf) whose lowered
    sharding differs from what the declared PartitionSpec demands —
    sharded on the wrong dim, sharded where declared replicated, or
    missing from the signature entirely. The large
    declared-sharded-but-replicated case is TPU302's (one finding per
    drift, the sharper rule wins)."""
    if not rec.sharded:
        return []
    out = []
    for side, i, spec, counts, nbytes in rec.declared_vs_lowered():
        declared_sharded = any(a is not None for a in spec)
        if declared_sharded and counts in ((), None) \
                and nbytes >= LARGE_BUFFER_BYTES:
            continue                      # TPU302's finding
        if counts is None:
            if declared_sharded:
                out.append(_finding(
                    "TPU303", rec,
                    f"{side}put leaf #{i} is declared "
                    f"{_fmt_spec(spec)} but carries no lowered "
                    "sharding (missing from the @main signature or "
                    "unspecified) — the declared layout never "
                    "reached the compiler"))
            continue
        expected = rec.expected_counts(spec, len(counts) or len(spec))
        if counts != expected:
            out.append(_finding(
                "TPU303", rec,
                f"{side}put leaf #{i} is declared {_fmt_spec(spec)} "
                f"(expects {_fmt_counts(expected)}) but lowered "
                f"{_fmt_counts(counts)} — the compiled layout "
                "drifted from the declared plan"))
    return out


def check_tpu304(rec):
    """TPU304 axis-unsafe collective shape: a collective whose GLOBAL
    payload exceeds the budget's declared axis-size-invariant bound.
    The bound is written over the serving geometry only (tokens,
    hidden, vocab, ...), so a payload that scales with the mesh —
    gathering an already-gathered activation, reducing a buffer that
    grew by axis_size — lands above it at ANY size: the bug class
    that makes mp=4 quietly move 2x mp=2's bytes."""
    budget = rec.axis_budget
    if budget is None or not rec.sites:
        return []
    out = []
    for s in rec.sites:
        for axis in s.axes:
            bound = _max_bound(rec, axis, s.kind)
            if bound is None:
                continue                  # undeclared kind: TPU301's
            if s.global_bytes > bound:
                out.append(_finding(
                    "TPU304", rec,
                    f"{s.kind} over axis '{axis}' carries a "
                    f"{s.global_bytes}-byte global payload, declared "
                    f"bound {bound} bytes — the payload is not "
                    "invariant to the axis size it crosses"))
    return out


def check_tpu305(rec):
    """TPU305 dcn-hostile collective: a collective crossing a budget
    axis declared "dcn" (slow inter-slice link) from a latency-bound
    position — a per-token program (the decode/verify host loop body)
    or an on-device loop body. Forward-looking for ROADMAP item 1:
    the moment a 'pp' DCN axis exists, a per-token all-gather across
    it fails here instead of flooring serving throughput on
    hardware."""
    budget = rec.axis_budget
    if budget is None or not rec.sites:
        return []
    slow = set(budget.slow_axes())
    if not slow:
        return []
    out = []
    for s in rec.sites:
        hot = rec.contract.per_token or s.in_loop
        for axis in s.axes:
            if axis in slow and hot:
                where = ("an on-device loop body" if s.in_loop
                         else "a per-token step")
                out.append(_finding(
                    "TPU305", rec,
                    f"{s.kind} ({s.global_bytes} bytes) crosses slow "
                    f"axis '{axis}' (link=dcn) from {where} — a "
                    "latency-bound collective on the inter-slice "
                    "network; restructure to overlap or batch it"))
    return out


#: rule id -> (name, description, checker). TPU300 is the meta-rule
#: for SHARD_BASELINE drift and unparseable lowered signatures
#: (reported by core, like tpu-verify's TPU100).
SHARD_RULES = {
    "TPU300": ("shard-drift",
               "per-program per-axis collective byte totals drifted "
               "from the committed SHARD_BASELINE.json", None),
    "TPU301": ("undeclared-resharding",
               "collective crosses an undeclared mesh axis/kind or "
               "moves more bytes than the per-axis budget allows",
               check_tpu301),
    "TPU302": ("replicated-large-buffer",
               "large buffer lowered replicated where the declared "
               "layout (pool_pspec/_tp_specs) says sharded",
               check_tpu302),
    "TPU303": ("pspec-layout-drift",
               "declared PartitionSpec plan disagrees with the "
               "program's lowered in/out sharding", check_tpu303),
    "TPU304": ("axis-unsafe-collective-shape",
               "collective payload exceeds the declared axis-size-"
               "invariant bound (bytes scale with the mesh)",
               check_tpu304),
    "TPU305": ("dcn-hostile-collective",
               "latency-bound (per-token / in-loop) collective "
               "crosses a declared slow (DCN) axis", check_tpu305),
}


def all_shard_rule_ids():
    return sorted(SHARD_RULES)


def check_record(rec):
    """Run every TPU3xx rule over one record. Contract waivers mark
    findings suppressed (same etiquette as the trace tier)."""
    findings = []
    for rule_id in all_shard_rule_ids():
        check = SHARD_RULES[rule_id][2]
        if check is None:
            continue
        found = check(rec)
        why = rec.contract.waived(rule_id)
        if why is not None:
            for f in found:
                f.suppressed = True
        findings.extend(found)
    return findings
