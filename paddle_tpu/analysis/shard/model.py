"""Sharding-layout model for tpu-shard: the parsed view of ONE
harvested program the TPU3xx rules consume.

Three extraction passes over a `TracedProgram` (the tpu-verify
harvest record — tpu-shard deliberately harvests NOTHING itself):

- `parse_main_shardings` reads the lowered StableHLO module's
  `@main` signature and returns, per argument and per result, the
  tensor shape/dtype and the `mhlo.sharding` attribute decoded to
  per-dim partition COUNTS — the form actually compiled, which is why
  the rules run on lowered shardings and not on source PartitionSpecs
  (a pspec the lowering dropped is exactly the bug class TPU302/303
  exist to catch).
- `collect_sites` walks the jaxpr (duck-typed, recursively — shard_map
  and loop bodies included) and captures every mesh collective as a
  `CollectiveSite`: kind, axes crossed, per-shard and global payload
  bytes, and whether it sits inside an on-device loop body.
- `eval_payload` evaluates an `AxisCollectiveBudget` payload-bound
  expression over the program's harvest geometry.

No jax import anywhere (the import-smoke contract shared with the
sibling tiers): jaxprs are walked by duck typing and the lowered
module is plain text.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from functools import cached_property

from ..trace.contracts import CollectiveBudget, resolve_budget
from ..trace.rules import COLLECTIVE_PRIMS

#: TPU302 threshold: a buffer at least this large that lowers
#: replicated where the declared layout says sharded is a real
#: HBM-doubling (weights, KV pool planes, adapter pages); smaller
#: leaves (biases, norm scales, scalar rows) replicate by design.
LARGE_BUFFER_BYTES = 1024

#: Primitives whose sub-jaxpr params are ON-DEVICE LOOP BODIES — a
#: collective inside one runs per iteration, not per dispatch
#: (TPU305's latency multiplier).
_LOOP_PRIMS = frozenset({"while", "scan"})

#: Collective kinds whose logical (global) payload is the GATHERED
#: output; every other kind's global payload is its operand.
_GATHER_KINDS = frozenset({"all_gather", "pgather"})

_ITEMSIZE = {
    "pred": 1, "i1": 1, "i4": 1, "ui4": 1, "i8": 1, "ui8": 1,
    "f8E4M3FN": 1, "f8E5M2": 1, "i16": 2, "ui16": 2, "f16": 2,
    "bf16": 2, "i32": 4, "ui32": 4, "f32": 4, "i64": 8, "ui64": 8,
    "f64": 8, "c64": 8, "c128": 16,
}

_MAIN_RE = re.compile(
    r"func\.func\s+(?:public\s+)?@main\((?P<args>.*?)\)\s*->\s*"
    r"(?:\((?P<res>.*?)\)|(?P<res1>tensor<[^>]*>))\s*"
    r"(?:attributes\b[^{]*)?\{", re.S)
_TENSOR_RE = re.compile(r"tensor<([0-9x]*)([A-Za-z][A-Za-z0-9]*)>")
_SHARDING_RE = re.compile(r'mhlo\.sharding\s*=\s*"([^"]*)"')
_DEVICES_RE = re.compile(r"devices=\[([0-9,]+)\]")


class ShardParseError(ValueError):
    """The lowered module's @main signature did not parse — reported
    as a TPU300 finding by the caller, never silently skipped."""


def _itemsize(dtype):
    return _ITEMSIZE.get(dtype, 4)


def _parse_tensor(text):
    """-> (shape tuple, dtype str, nbytes) from one `tensor<...>`."""
    m = _TENSOR_RE.search(text)
    if m is None:
        raise ShardParseError(f"no tensor type in {text[:80]!r}")
    dims, dtype = m.group(1), m.group(2)
    shape = tuple(int(d) for d in dims.split("x") if d)
    n = _itemsize(dtype)
    for d in shape:
        n *= d
    return shape, dtype, n


def _parse_sharding(text):
    """Decode one `mhlo.sharding` attribute value to per-dim partition
    counts: () = replicated/maximal, (1, 1, 1, 2, 1) = dim 3 split in
    two. None when the entry carries no sharding attribute at all
    (unspecified — jit chose; host args look like this)."""
    m = _SHARDING_RE.search(text)
    if m is None:
        return None
    val = m.group(1)
    if "devices=" not in val:
        return ()                      # {replicated} / {maximal ...}
    counts = tuple(int(d) for d in
                   _DEVICES_RE.search(val).group(1).split(","))
    if "last_tile_dim_replicate" in val:
        counts = counts[:-1]
    return counts if any(c > 1 for c in counts) else ()


def parse_main_shardings(lowered_text):
    """-> (args, results): two lists of (shape, dtype, nbytes,
    partition_counts) tuples for the lowered module's @main
    signature. Raises ShardParseError when the signature is missing
    or malformed."""
    m = _MAIN_RE.search(lowered_text)
    if m is None:
        raise ShardParseError("no @main signature in lowered module")
    args = []
    arg_text = m.group("args").strip()
    if arg_text:
        for part in re.split(r",\s*(?=%arg\d+\s*:)", arg_text):
            shape, dtype, nbytes = _parse_tensor(part)
            args.append((shape, dtype, nbytes, _parse_sharding(part)))
    results = []
    res_text = (m.group("res") or m.group("res1") or "").strip()
    if res_text:
        for part in re.split(r",\s*(?=tensor<)", res_text):
            shape, dtype, nbytes = _parse_tensor(part)
            results.append((shape, dtype, nbytes,
                            _parse_sharding(part)))
    return args, results


# ---------------------------------------------------------------------------
# collective sites (duck-typed jaxpr walk; no jax import)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CollectiveSite:
    """One mesh-collective equation in a harvested program."""

    kind: str                # primitive name (all_gather, psum, ...)
    axes: tuple              # mesh axis names it crosses
    axis_size: int           # total participants across those axes
    shard_bytes: int         # per-participant operand bytes
    global_bytes: int        # logical payload (gathered out / operand)
    in_loop: bool            # inside an on-device while/scan body

    @property
    def moved_bytes(self):
        """Wire-cost proxy: bytes each participant RECEIVES from its
        peers (the ring lower bound) — shard payload x (axis_size-1)
        for gathers and reductions alike; see DESIGN_DECISIONS r23."""
        return self.shard_bytes * max(self.axis_size - 1, 0)


def _aval_bytes(var):
    aval = getattr(var, "aval", None)
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    n = getattr(getattr(aval, "dtype", None), "itemsize", 4)
    for d in shape:
        n *= int(d)
    return n


def _site_axes(params):
    names = params.get("axis_name", params.get("axes", ()))
    if not isinstance(names, (tuple, list)):
        names = (names,)
    return tuple(n for n in names if isinstance(n, str))


def _inner(obj):
    if hasattr(obj, "eqns"):
        return obj
    inner = getattr(obj, "jaxpr", None)
    return inner if hasattr(inner, "eqns") else None


def collect_sites(jaxpr, axis_sizes):
    """Every CollectiveSite in `jaxpr`, recursing into sub-jaxprs
    (shard_map bodies, loop bodies — marked `in_loop` below a
    while/scan). `axis_sizes` maps mesh axis name -> size; a gather's
    own `axis_size` param wins when present."""
    sites = []

    def walk(closed, in_loop):
        top = _inner(closed)
        if top is None:
            return
        for eqn in top.eqns:
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMS:
                axes = _site_axes(eqn.params)
                size = eqn.params.get("axis_size")
                if size is None:
                    size = 1
                    for a in axes:
                        size *= int(axis_sizes.get(a, 1))
                shard = sum(_aval_bytes(v) for v in eqn.invars)
                if name in _GATHER_KINDS:
                    glob = sum(_aval_bytes(v) for v in eqn.outvars)
                else:
                    glob = shard
                sites.append(CollectiveSite(
                    kind=name, axes=axes, axis_size=int(size),
                    shard_bytes=shard, global_bytes=glob,
                    in_loop=in_loop))
            below = in_loop or name in _LOOP_PRIMS
            for v in eqn.params.values():
                vs = v if isinstance(v, (list, tuple)) else (v,)
                for sub in vs:
                    if _inner(sub) is not None:
                        walk(sub, below)

    walk(jaxpr, False)
    return sites


# ---------------------------------------------------------------------------
# payload-bound expressions
# ---------------------------------------------------------------------------

_EXPR_RE = re.compile(r"^[\sa-z_0-9*+\-/()]*$")


def eval_payload(expr, geometry):
    """Evaluate one AxisCollectiveBudget payload-bound expression
    (bytes) over the harvest geometry symbols. The grammar is plain
    integer arithmetic over [a-z_] symbols — anything else is a
    declaration error, not code execution."""
    if not _EXPR_RE.match(expr):
        raise ValueError(f"bad payload expression {expr!r}")
    try:
        val = eval(expr, {"__builtins__": {}}, dict(geometry))
    except Exception as e:
        raise ValueError(
            f"payload expression {expr!r} does not evaluate over "
            f"geometry {sorted(geometry)}: {e}") from e
    return int(val)


# ---------------------------------------------------------------------------
# the record
# ---------------------------------------------------------------------------

@dataclass
class ShardRecord:
    """One harvested program, parsed for the TPU3xx rules. Wraps the
    tpu-verify TracedProgram (`prog`) — same contract anchor, same
    config key, so a finding's stable ID matches across tiers'
    conventions."""

    prog: object                     # trace.rules.TracedProgram
    axis_sizes: dict = field(default_factory=dict)
    parse_error: str = ""

    def __post_init__(self):
        if not self.axis_sizes:
            self.axis_sizes = {"mp": self.prog.mp}

    @property
    def key(self):
        return self.prog.key

    @property
    def contract(self):
        return self.prog.contract

    @property
    def sharded(self):
        """Any mesh axis with more than one participant?"""
        return any(int(s) > 1 for s in self.axis_sizes.values())

    @cached_property
    def budget(self):
        """The contract's resolved budget — axis/byte checks need the
        AxisCollectiveBudget form; a legacy count-only
        CollectiveBudget declares NO axes (every collective is then an
        undeclared resharding, which is the point: the per-axis gate
        requires the per-axis table)."""
        return resolve_budget(self.contract)

    @property
    def axis_budget(self):
        b = self.budget
        return None if isinstance(b, CollectiveBudget) else b

    @cached_property
    def sites(self):
        return collect_sites(self.prog.jaxpr, self.axis_sizes)

    @cached_property
    def _signature(self):
        try:
            return parse_main_shardings(self.prog.lowered_text)
        except ShardParseError as e:
            # surfaced by core.analyze_programs as a TPU300 finding
            self.parse_error = str(e)
            return [], []

    @property
    def lowered_in(self):
        return self._signature[0]

    @property
    def lowered_out(self):
        return self._signature[1]

    def declared_vs_lowered(self):
        """-> [(side, index, declared, lowered, nbytes)] pairing every
        DECLARED leaf layout with the lowered signature entry at the
        same position (inputs then outputs). Leaves with no
        declaration (None — host args) are skipped; a declared leaf
        beyond the lowered signature pairs with lowered=None."""
        out = []
        for side, declared, lowered in (
                ("in", self.prog.declared_in_specs, self.lowered_in),
                ("out", self.prog.declared_out_specs,
                 self.lowered_out)):
            if declared is None:
                continue
            for i, spec in enumerate(declared):
                if spec is None:
                    continue
                low = lowered[i] if i < len(lowered) else None
                counts = low[3] if low is not None else None
                nbytes = low[2] if low is not None else 0
                out.append((side, i, spec, counts, nbytes))
        return out

    def expected_counts(self, spec, ndim):
        """Partition counts a declared per-dim axis-name tuple demands
        of the lowered sharding, padded to the leaf's rank; () for a
        declared-replicated leaf."""
        counts = []
        for k in range(ndim):
            axis = spec[k] if k < len(spec) else None
            if axis is None:
                counts.append(1)
            else:
                axes = axis if isinstance(axis, tuple) else (axis,)
                n = 1
                for a in axes:
                    n *= int(self.axis_sizes.get(a, 1))
                counts.append(n)
        return tuple(counts) if any(c > 1 for c in counts) else ()

    @cached_property
    def axis_totals(self):
        """{axis: {kind: {"count": n, "moved_bytes": b}}} — the unit
        of the SHARD_BASELINE.json drift snapshot. Collectives that
        lower away at axis size 1 contribute nothing (mp=1 programs
        have no collectives to begin with)."""
        totals = {}
        for s in self.sites:
            for axis in s.axes:
                per = totals.setdefault(axis, {}).setdefault(
                    s.kind, {"count": 0, "moved_bytes": 0})
                per["count"] += 1
                per["moved_bytes"] += s.moved_bytes
        return totals


def build_record(prog, axis_sizes=None):
    return ShardRecord(prog=prog, axis_sizes=dict(axis_sizes or {}))
