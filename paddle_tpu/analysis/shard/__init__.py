"""tpu-shard — static sharding-layout & per-axis collective-byte
analysis.

The fourth analysis tier (TPU3xx): tpu-lint (`paddle_tpu.analysis`,
AST trace-safety), tpu-verify (`analysis.trace`, jaxpr contracts) and
tpu-race (`analysis.race`, host concurrency) check what programs DO;
this package checks where their data LIVES and what the mesh MOVES —
every collective in every harvested program classified by mesh axis
with its moved bytes computed from operand shapes/dtypes and checked
against the `jit.introspect.AxisCollectiveBudget` table, and every
declared PartitionSpec (`_tp_specs`, `pool_pspec()`, the adapter
pool's `pool_pspecs()`) compared against the lowered module's actual
`mhlo.sharding` attributes. It is the readiness gate for the pp/DCN
mesh axis of ROADMAP item 1: per-axis byte totals are drift-pinned in
`SHARD_BASELINE.json` (TPU300), and the DCN-hostile rule (TPU305) is
armed before the slow axis exists. `verify_shards` is the in-process
API the tier-1 gate uses; `tools/tpu_shard.py` is the CLI.

LAZY package init (PEP 562), like the sibling tiers: nothing here
loads until analysis actually runs, and importing it never
initializes a JAX backend (the model walks jaxprs by duck typing and
parses lowered StableHLO text — no jax import anywhere in the tier).
"""
from __future__ import annotations

_EXPORTS = {
    "model": ("ShardRecord", "CollectiveSite", "build_record",
              "parse_main_shardings", "eval_payload",
              "LARGE_BUFFER_BYTES"),
    "rules": ("SHARD_RULES", "all_shard_rule_ids", "check_record"),
    "core": ("ShardResult", "analyze_programs", "verify_shards",
             "snapshot_of", "load_shard_baseline",
             "write_shard_baseline", "compare_snapshot",
             "load_baseline", "apply_baseline", "write_baseline",
             "BaselineError", "SUPPRESS_TAG", "Finding",
             "DEFAULT_SHARD_BASELINE", "_REPO_ROOT"),
    "cli": ("main", "DEFAULT_BASELINE"),
}

__all__ = sorted(n for names in _EXPORTS.values() for n in names
                 if not n.startswith("_"))

_WHENCE = {name: mod for mod, names in _EXPORTS.items()
           for name in names}


def __getattr__(name):
    mod = _WHENCE.get(name)
    if mod is not None:
        import importlib

        return getattr(
            importlib.import_module(f".{mod}", __name__), name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_WHENCE))
