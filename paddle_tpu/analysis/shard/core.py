"""tpu-shard driver: records, drift snapshot, suppressions, results.

Consumes the tpu-verify harvest (`analysis.trace.harvest`) — tpu-shard
lowers NOTHING itself, so the two tiers can never disagree about what
a program's jaxpr or StableHLO looks like — wraps each TracedProgram
in a `model.ShardRecord`, runs the TPU3xx rules, and compares
per-program per-axis byte totals against the committed
`SHARD_BASELINE.json` (drift = TPU300; the reviewed acceptance path is
`tools/tpu_shard.py --write-shard-baseline`, mirroring tpu-verify's
TRACE_BASELINE).

Inline suppressions use the `tpu-shard` tag (same-line, at the
contract's declaration anchor), a namespace disjoint from
tpu-lint's and tpu-race's — `# tpu-shard: disable=TPU301`.
"""
from __future__ import annotations

import json
import os

from ..baseline import (BaselineError, apply_baseline, load_baseline,
                        write_baseline)
from ..findings import (Finding, apply_suppressions, assign_ids,
                        parse_suppressions)
from .model import build_record
from .rules import all_shard_rule_ids, check_record

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

#: Committed drift snapshot (repo root, TRACE_BASELINE.json precedent).
DEFAULT_SHARD_BASELINE = os.path.join(_REPO_ROOT, "SHARD_BASELINE.json")

SUPPRESS_TAG = "tpu-shard"

__all__ = [
    "ShardResult", "analyze_programs", "verify_shards", "snapshot_of",
    "load_shard_baseline", "write_shard_baseline", "compare_snapshot",
    "load_baseline", "apply_baseline", "write_baseline",
    "BaselineError", "Finding", "SUPPRESS_TAG",
    "DEFAULT_SHARD_BASELINE",
]


class ShardResult:
    """Mirror of the sibling tiers' Result records."""

    def __init__(self):
        self.findings = []
        self.records = []
        self.stale_baseline = []        # findings-baseline ids
        self.stale_shard_baseline = []  # snapshot keys

    @property
    def programs(self):
        return [r.prog for r in self.records]

    def new_findings(self):
        return [f for f in self.findings
                if not f.suppressed and not f.baselined]

    def per_rule_counts(self):
        out = {r: 0 for r in all_shard_rule_ids()}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


# ---------------------------------------------------------------------------
# drift snapshot (SHARD_BASELINE.json / TPU300)
# ---------------------------------------------------------------------------

def snapshot_of(records):
    """program key -> per-axis per-kind {count, moved_bytes} totals —
    the unit of the committed byte-drift baseline. Every harvested
    program gets an entry (mp=1 and conv programs pin an EMPTY axes
    map: growing a collective where none existed is drift too)."""
    return {rec.key: {"axes": {
        axis: {kind: dict(v) for kind, v in sorted(kinds.items())}
        for axis, kinds in sorted(rec.axis_totals.items())}}
        for rec in records}


def load_shard_baseline(path):
    with open(path) as f:
        data = json.load(f)
    return data.get("programs", data)


def write_shard_baseline(path, records):
    with open(path, "w") as f:
        json.dump({"version": 1, "programs": snapshot_of(records)},
                  f, indent=1, sort_keys=True)
        f.write("\n")
    return len(records)


def _diff_axes(cur, base):
    bits = []
    c, b = cur.get("axes", {}), base.get("axes", {})
    for axis in sorted(set(c) | set(b)):
        ck, bk = c.get(axis, {}), b.get(axis, {})
        for kind in sorted(set(ck) | set(bk)):
            cv = ck.get(kind, {"count": 0, "moved_bytes": 0})
            bv = bk.get(kind, {"count": 0, "moved_bytes": 0})
            if cv != bv:
                bits.append(
                    f"{axis}/{kind} {bv['count']}x/"
                    f"{bv['moved_bytes']}B -> {cv['count']}x/"
                    f"{cv['moved_bytes']}B")
    return "; ".join(bits[:6]) + (" ..." if len(bits) > 6 else "")


def compare_snapshot(records, baseline):
    """-> (drift findings [TPU300], stale baseline keys). Exact-match
    per-axis byte comparison — any change in what a program moves
    over the mesh fails loudly until --write-shard-baseline
    re-snapshots it and the diff is reviewed."""
    current = snapshot_of(records)
    by_key = {rec.key: rec for rec in records}
    findings = []
    for key in sorted(current):
        rec = by_key[key]
        if key not in baseline:
            findings.append(Finding(
                rule="TPU300", path=rec.contract.declared_at, line=1,
                col=0, qualname=rec.contract.name,
                source=rec.prog.config,
                message=f"program {key} has no SHARD_BASELINE.json "
                        "entry — run tools/tpu_shard.py "
                        "--write-shard-baseline and review the "
                        "snapshot"))
        elif current[key] != baseline[key]:
            findings.append(Finding(
                rule="TPU300", path=rec.contract.declared_at, line=1,
                col=0, qualname=rec.contract.name,
                source=rec.prog.config,
                message=f"program {key} drifted from "
                        "SHARD_BASELINE.json: "
                        f"{_diff_axes(current[key], baseline[key])}"
                        " — intentional? re-snapshot with "
                        "--write-shard-baseline"))
    stale = sorted(set(baseline) - set(current))
    return findings, stale


# ---------------------------------------------------------------------------
# the full check
# ---------------------------------------------------------------------------

def _apply_shard_suppressions(findings, sources=None):
    """Same-line `# tpu-shard: disable=...` suppression at each
    finding's anchor (the contract declaration file). `sources` maps
    path -> text for tests; otherwise anchors resolve against the
    repo root."""
    by_path = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    for path, fs in by_path.items():
        src = (sources or {}).get(path)
        if src is None:
            full = path if os.path.isabs(path) \
                else os.path.join(_REPO_ROOT, path)
            if not os.path.exists(full):
                continue
            with open(full, encoding="utf-8") as fh:
                src = fh.read()
        apply_suppressions(
            fs, parse_suppressions(src, tag=SUPPRESS_TAG))
    return findings


def analyze_programs(programs, baseline=None, shard_baseline=None,
                     axis_sizes=None, sources=None):
    """Run the TPU3xx rules (+ drift comparison) over already-
    harvested TracedPrograms — the in-process API (the gate and the
    fixtures drive this; `verify_shards` adds the harvest).

    `baseline` is a loaded findings baseline ({id: entry}) or None;
    `shard_baseline` a loaded snapshot dict, a path, or None to skip
    drift checking; `axis_sizes` overrides the mesh axis sizes
    ({"mp": prog.mp} by default) for fixture meshes."""
    res = ShardResult()
    res.records = [build_record(p, axis_sizes) for p in programs]
    for rec in res.records:
        res.findings.extend(check_record(rec))
        if rec.parse_error:
            res.findings.append(Finding(
                rule="TPU300", path=rec.contract.declared_at, line=1,
                col=0, qualname=rec.contract.name,
                source=rec.prog.config,
                message=f"lowered module for {rec.key} did not "
                        f"parse: {rec.parse_error} — the sharding "
                        "surface is unverifiable"))
    if isinstance(shard_baseline, str):
        shard_baseline = load_shard_baseline(shard_baseline)
    if shard_baseline is not None:
        drift, res.stale_shard_baseline = compare_snapshot(
            res.records, shard_baseline)
        res.findings.extend(drift)
    _apply_shard_suppressions(res.findings, sources)
    assign_ids(res.findings)
    if baseline:
        # TPU300 is excluded from the findings baseline, exactly like
        # tpu-verify's TPU100: a drift finding's stable ID hashes the
        # program key, not the drift content, so one grandfathered
        # entry would mask every FUTURE byte drift of that program.
        # Drift acceptance is --write-shard-baseline, reviewed.
        res.stale_baseline = apply_baseline(
            [f for f in res.findings if f.rule != "TPU300"], baseline)
    res.findings.sort(key=lambda f: (f.path, f.qualname, f.source,
                                     f.rule))
    return res


def _norm_prefix(path):
    rel = os.path.relpath(os.path.abspath(path), _REPO_ROOT)
    return rel.replace(os.sep, "/").rstrip("/")


def filter_programs(programs, paths):
    """Restrict to programs whose contract is DECLARED under one of
    `paths` (repo-relative or absolute files/directories) — the CLI's
    positional-path semantics: `tools/tpu_shard.py paddle_tpu/`
    checks every program declared in the tree."""
    if not paths:
        return list(programs)
    prefixes = [_norm_prefix(p) for p in paths]
    out = []
    for p in programs:
        declared = p.contract.declared_at
        if any(declared == pre or declared.startswith(pre + "/")
               for pre in prefixes):
            out.append(p)
    return out


def verify_shards(matrix=None, paths=None, baseline=None,
                  shard_baseline="auto"):
    """Harvest the tpu-verify matrix and run every TPU3xx rule + the
    byte-drift comparison. `shard_baseline` is a path, a loaded
    snapshot dict, "auto" (the committed SHARD_BASELINE.json when
    present) or None."""
    from ..trace.harvest import harvest

    programs = filter_programs(harvest(matrix), paths)
    if shard_baseline == "auto":
        shard_baseline = DEFAULT_SHARD_BASELINE \
            if os.path.exists(DEFAULT_SHARD_BASELINE) else None
    return analyze_programs(programs, baseline=baseline,
                            shard_baseline=shard_baseline)
