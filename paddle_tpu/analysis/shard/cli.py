"""tpu-shard CLI implementation (thin wrapper lives in
tools/tpu_shard.py), mirroring the sibling tiers' interface.

Exit codes: 0 clean (against baselines), 1 findings, 2 usage/baseline
error — the tpu-lint convention.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from ..baseline import BaselineError, load_baseline, write_baseline
from .core import (DEFAULT_SHARD_BASELINE, _REPO_ROOT,
                   load_shard_baseline, verify_shards,
                   write_shard_baseline)
from .rules import SHARD_RULES, all_shard_rule_ids

DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "tools",
                                "tpu_shard_baseline.json")


def _print_stats(res, out):
    counts = res.per_rule_counts()
    suppressed = sum(1 for f in res.findings if f.suppressed)
    baselined = sum(1 for f in res.findings if f.baselined)
    print("-- tpu-shard stats -----------------------------------",
          file=out)
    print(f"programs analyzed: {len(res.records)}", file=out)
    for rec in res.records:
        axes = {axis: {k: f"{v['count']}x/{v['moved_bytes']}B"
                       for k, v in kinds.items()}
                for axis, kinds in rec.axis_totals.items()}
        print(f"  {rec.key}: axes={axes or '{}'}", file=out)
    for rule in all_shard_rule_ids():
        name = SHARD_RULES[rule][0]
        print(f"{rule} {name:<30} {counts.get(rule, 0)}", file=out)
    print(f"suppressed inline/waived: {suppressed}   "
          f"baselined: {baselined}", file=out)


def main(argv=None, programs=None):
    """`programs` injects already-harvested TracedPrograms (the unit
    tests' seam — the default path harvests the full matrix)."""
    ap = argparse.ArgumentParser(
        prog="tpu_shard",
        description="static sharding-layout & per-axis "
                    "collective-byte analyzer")
    ap.add_argument("paths", nargs="*",
                    help="files or directories; only programs whose "
                         "contract is DECLARED under one of them are "
                         "checked (default: all harvested programs)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--baseline", default=None,
                    help="findings baseline JSON ('none' disables; "
                         "default: tools/tpu_shard_baseline.json "
                         "when present)")
    ap.add_argument("--write-baseline", metavar="PATH",
                    help="write current new findings as a baseline "
                         "skeleton (justifications left empty on "
                         "purpose) and exit")
    ap.add_argument("--shard-baseline", default=None,
                    help="byte-drift snapshot JSON ('none' disables; "
                         "default: SHARD_BASELINE.json at the repo "
                         "root when present)")
    ap.add_argument("--write-shard-baseline", nargs="?",
                    metavar="PATH", const=DEFAULT_SHARD_BASELINE,
                    help="re-snapshot per-program per-axis collective "
                         "byte totals (default path: the committed "
                         "SHARD_BASELINE.json) and exit")
    ap.add_argument("--stats", action="store_true",
                    help="print per-program axis/byte totals and "
                         "per-rule finding counts")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_shard_rule_ids():
            name, desc, _ = SHARD_RULES[rule]
            print(f"{rule}  {name:<30} {desc}")
        return 0

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"tpu_shard: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2

    baseline = {}
    if args.baseline != "none" and not args.write_baseline:
        bpath = args.baseline or (
            DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE)
            else None)
        if args.baseline and not os.path.exists(args.baseline):
            print(f"tpu_shard: baseline not found: {args.baseline}",
                  file=sys.stderr)
            return 2
        if bpath:
            try:
                baseline = load_baseline(bpath)
            except (BaselineError, json.JSONDecodeError) as e:
                print(f"tpu_shard: bad baseline {bpath}: {e}",
                      file=sys.stderr)
                return 2

    # resolve AND load the drift snapshot BEFORE the (expensive)
    # harvest — a corrupt file is a usage error, not a late traceback
    shard_baseline = None
    if not args.write_shard_baseline and args.shard_baseline != "none":
        sb_path = args.shard_baseline or (
            DEFAULT_SHARD_BASELINE
            if os.path.exists(DEFAULT_SHARD_BASELINE) else None)
        if args.shard_baseline and not os.path.exists(
                args.shard_baseline):
            print("tpu_shard: shard baseline not found: "
                  f"{args.shard_baseline}", file=sys.stderr)
            return 2
        if sb_path:
            try:
                shard_baseline = load_shard_baseline(sb_path)
            except (json.JSONDecodeError, OSError) as e:
                print(f"tpu_shard: bad shard baseline {sb_path}: {e}",
                      file=sys.stderr)
                return 2

    try:
        if programs is not None:
            from .core import analyze_programs, filter_programs

            res = analyze_programs(
                filter_programs(programs, args.paths),
                baseline=baseline, shard_baseline=shard_baseline)
        else:
            res = verify_shards(paths=args.paths, baseline=baseline,
                                shard_baseline=shard_baseline)
    except RuntimeError as e:
        print(f"tpu_shard: {e}", file=sys.stderr)
        return 2

    if args.write_shard_baseline:
        n = write_shard_baseline(args.write_shard_baseline,
                                 res.records)
        print(f"snapshotted {n} programs to "
              f"{args.write_shard_baseline} — review the diff before "
              "committing")
        return 0

    if args.write_baseline:
        # TPU300 drift is never grandfatherable (see core) — its
        # acceptance path is --write-shard-baseline, reviewed
        n = write_baseline(args.write_baseline,
                           [f for f in res.new_findings()
                            if f.rule != "TPU300"])
        print(f"wrote {n} entries to {args.write_baseline} — add a "
              "justification to each (the loader rejects empty ones; "
              "TPU300 drift is never grandfatherable)")
        return 0

    new = res.new_findings()
    if args.format == "json":
        doc = {
            "findings": [f.to_dict() for f in new],
            "suppressed": sum(1 for f in res.findings if f.suppressed),
            "baselined": sum(1 for f in res.findings if f.baselined),
            "stale_baseline": res.stale_baseline,
            "stale_shard_baseline": res.stale_shard_baseline,
            "programs": [rec.key for rec in res.records],
        }
        print(json.dumps(doc, indent=1))
    else:
        for f in new:
            print(f.render())
        for bid in res.stale_baseline:
            print(f"note: stale baseline entry {bid} — no current "
                  "finding matches; remove it")
        for key in res.stale_shard_baseline:
            print(f"note: stale SHARD_BASELINE entry {key} — no "
                  "current program matches; re-snapshot")
        if not new:
            print(f"tpu-shard clean: {len(res.records)} programs, "
                  f"{sum(1 for f in res.findings if f.baselined)} "
                  "baselined, "
                  f"{sum(1 for f in res.findings if f.suppressed)} "
                  "suppressed")
    if args.stats:
        _print_stats(res, sys.stdout)
    return 1 if new else 0
