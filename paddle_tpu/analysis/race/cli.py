"""tpu-race CLI implementation (thin wrapper lives in
tools/tpu_race.py).

Exit codes: 0 clean (against baseline), 1 findings, 2 usage/baseline
error — the tpu-lint convention.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .core import (BaselineError, _REPO_ROOT, all_race_rule_ids,
                   analyze_paths, load_baseline, write_baseline)
from .rules import RACE_RULES

DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "tools",
                                "tpu_race_baseline.json")


def _print_stats(res, out):
    counts = res.per_rule_counts()
    suppressed = sum(1 for f in res.findings if f.suppressed)
    baselined = sum(1 for f in res.findings if f.baselined)
    print("-- tpu-race stats ------------------------------------",
          file=out)
    print(f"files analyzed: {len(res.files)}", file=out)
    if res.parse_errors:
        print(f"UNPARSEABLE files: {len(res.parse_errors)} "
              "(reported as TPU200 findings, not skipped):", file=out)
        for path, msg in res.parse_errors:
            print(f"  {path}: {msg}", file=out)
    else:
        print("unparseable files: 0", file=out)
    for rule in all_race_rule_ids():
        name = RACE_RULES[rule][0]
        print(f"{rule} {name:<26} {counts.get(rule, 0)}", file=out)
    print(f"suppressed inline: {suppressed}   baselined: {baselined}",
          file=out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tpu_race",
        description="static thread-safety & allocator-lifetime "
                    "analyzer")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: paddle_tpu, "
                         "bench*.py, tools)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON ('none' disables; default: "
                         "tools/tpu_race_baseline.json when present)")
    ap.add_argument("--write-baseline", metavar="PATH",
                    help="write current new findings as a baseline "
                         "skeleton (justifications left empty on "
                         "purpose) and exit")
    ap.add_argument("--stats", action="store_true",
                    help="print per-rule finding counts and "
                         "analyzed/unparseable file totals")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_race_rule_ids():
            name, desc, _ = RACE_RULES[rule]
            print(f"{rule}  {name:<26} {desc}")
        return 0

    paths = args.paths
    if not paths:
        import glob

        paths = ([os.path.join(_REPO_ROOT, "paddle_tpu")]
                 + sorted(glob.glob(os.path.join(_REPO_ROOT,
                                                 "bench*.py")))
                 + [os.path.join(_REPO_ROOT, "tools")])
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"tpu_race: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2

    baseline = {}
    if args.baseline != "none" and not args.write_baseline:
        bpath = args.baseline or (
            DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE)
            else None)
        if args.baseline and not os.path.exists(args.baseline):
            print(f"tpu_race: baseline not found: {args.baseline}",
                  file=sys.stderr)
            return 2
        if bpath:
            try:
                baseline = load_baseline(bpath)
            except (BaselineError, json.JSONDecodeError) as e:
                print(f"tpu_race: bad baseline {bpath}: {e}",
                      file=sys.stderr)
                return 2

    res = analyze_paths(paths, baseline=baseline)

    if args.write_baseline:
        n = write_baseline(args.write_baseline, res.new_findings())
        print(f"wrote {n} entries to {args.write_baseline} — add a "
              "justification to each (the loader rejects empty ones)")
        return 0

    new = res.new_findings()
    if args.format == "json":
        doc = {
            "findings": [f.to_dict() for f in new],
            "suppressed": sum(1 for f in res.findings if f.suppressed),
            "baselined": sum(1 for f in res.findings if f.baselined),
            "stale_baseline": res.stale_baseline,
            "files": len(res.files),
            "parse_errors": [
                {"path": p, "message": m} for p, m in res.parse_errors],
        }
        print(json.dumps(doc, indent=1))
    else:
        for f in new:
            print(f.render())
        for bid in res.stale_baseline:
            print(f"note: stale baseline entry {bid} — no current "
                  "finding matches; remove it")
        if not new:
            print(f"tpu-race clean: {len(res.files)} files, "
                  f"{sum(1 for f in res.findings if f.baselined)} "
                  "baselined, "
                  f"{sum(1 for f in res.findings if f.suppressed)} "
                  "suppressed")
    if args.stats:
        _print_stats(res, sys.stdout)
    return 1 if new else 0
