"""tpu-race — static thread-safety & allocator-lifetime analysis.

The third analysis tier (TPU2xx): tpu-lint (`paddle_tpu.analysis`,
AST trace-safety) and tpu-verify (`analysis.trace`, jaxpr contracts)
check the traced programs; this package checks the host-side
concurrency AROUND them — lock discipline over shared mutable state,
thread-escape of helper callables, and the dispatch/complete/release
ordering that keeps the async engine core's allocators zombie-free
(DESIGN_DECISIONS r21/r22). `analyze_paths` is the in-process API the
tier-1 gate uses; `tools/tpu_race.py` is the CLI.

LAZY package init (PEP 562), like the sibling tiers: nothing here
loads until analysis actually runs, and importing it never
initializes a JAX backend (the model reads only
`paddle_tpu.jit.introspect`, pure metadata).
"""
from __future__ import annotations

_EXPORTS = {
    "core": ("analyze_file", "analyze_paths", "collect_files",
             "Finding", "Result", "RACE_RULES", "all_race_rule_ids",
             "load_baseline", "apply_baseline", "write_baseline",
             "BaselineError", "RaceModuleAnalysis", "SUPPRESS_TAG",
             "_REPO_ROOT"),
    "cli": ("main", "DEFAULT_BASELINE"),
}

__all__ = sorted(n for names in _EXPORTS.values() for n in names
                 if not n.startswith("_"))

_WHENCE = {name: mod for mod, names in _EXPORTS.items()
           for name in names}


def __getattr__(name):
    mod = _WHENCE.get(name)
    if mod is not None:
        import importlib

        return getattr(
            importlib.import_module(f".{mod}", __name__), name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_WHENCE))
