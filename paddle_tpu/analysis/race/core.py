"""tpu-race — static thread-safety & allocator-lifetime analysis.

The third analysis tier: tpu-lint (TPU0xx) checks the python that
tracing erases, tpu-verify (TPU1xx) checks what tracing produces, and
tpu-race (TPU2xx) checks the host-side concurrency AROUND the traced
programs — lock discipline over shared mutable state and the
dispatch/complete/release ordering of the async engine core.
`analyze_paths` is the in-process API the tier-1 gate uses;
`tools/tpu_race.py` is the CLI.

Importing this package must not initialize a JAX backend — it reads
only `paddle_tpu.jit.introspect` (pure metadata) from the framework,
through the same `ModuleAnalysis` machinery tpu-lint uses.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..baseline import (BaselineError, apply_baseline, load_baseline,
                        write_baseline)
from ..core import _display_path, _module_name, _REPO_ROOT, collect_files
from ..findings import (Finding, apply_suppressions, assign_ids,
                        parse_suppressions)
from .model import RaceModuleAnalysis
from .rules import RACE_RULES, all_race_rule_ids

__all__ = ["analyze_file", "analyze_paths", "collect_files", "Finding",
           "Result", "RACE_RULES", "all_race_rule_ids",
           "load_baseline", "apply_baseline", "write_baseline",
           "BaselineError", "RaceModuleAnalysis", "_REPO_ROOT"]

#: Same-line suppression tag: `# tpu-race: disable=TPU203`.
SUPPRESS_TAG = "tpu-race"


@dataclass
class Result:
    findings: list = field(default_factory=list)
    files: list = field(default_factory=list)
    parse_errors: list = field(default_factory=list)   # (path, message)
    stale_baseline: list = field(default_factory=list)

    def new_findings(self):
        return [f for f in self.findings
                if not f.suppressed and not f.baselined]

    def per_rule_counts(self):
        out = {r: 0 for r in all_race_rule_ids()}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def analyze_file(path, src=None):
    """-> (findings, model) for one file (IDs not yet assigned). A
    syntax error yields a single TPU200 finding — unparseable files
    are REPORTED, never silently dropped."""
    display = _display_path(path)
    if src is None:
        with open(path, encoding="utf-8", errors="replace") as f:
            src = f.read()
    try:
        mod = RaceModuleAnalysis(display, src,
                                 module_name=_module_name(path))
    except SyntaxError as e:
        return [Finding(rule="TPU200", path=display,
                        line=e.lineno or 1, col=(e.offset or 1) - 1,
                        message=f"unparseable file: {e.msg}")], None
    findings = []
    for rule_id in all_race_rule_ids():
        check = RACE_RULES[rule_id][2]
        if check is not None:
            findings.extend(check(mod))
    apply_suppressions(findings,
                       parse_suppressions(src, tag=SUPPRESS_TAG))
    return findings, mod


def analyze_paths(paths, baseline=None):
    """Analyze files/directories. `baseline` is {id: entry} (see
    load_baseline). Returns Result with stable IDs assigned and
    suppressions/baseline applied."""
    res = Result()
    for path in collect_files(paths):
        findings, _mod = analyze_file(path)
        res.files.append(_display_path(path))
        for f in findings:
            if f.rule == "TPU200":
                res.parse_errors.append((f.path, f.message))
        res.findings.extend(findings)
    assign_ids(res.findings)
    if baseline:
        res.stale_baseline = apply_baseline(res.findings, baseline)
    else:
        res.stale_baseline = []
    res.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return res
