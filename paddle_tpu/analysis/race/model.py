"""tpu-race analysis model: per-module concurrency facts.

Builds on the tpu-lint `ModuleAnalysis` (alias resolution, scope tree,
jit-reachability) and adds the three fact tables the TPU2xx rules
consume:

1. **Thread escape** — which local callables can run on a helper
   thread, seeded at `threading.Thread(target=...)` / executor
   `.submit(fn, ...)` call sites (`introspect.THREAD_SPAWN_CALLS` /
   `EXECUTOR_SUBMIT_METHODS`) and propagated through module-local
   calls — the same worklist shape as tpu-lint's traced-ness pass C.
2. **Lock sets** — which attribute / module names are locks (assigned
   from `introspect.LOCK_CONSTRUCTORS`, or from a value whose own
   name says lock), and for every attribute/global access, which
   locks are lexically held (`with <lock>:` regions) or asserted held
   by the caller via a same-line `# guarded-by: <lock>` annotation.
3. **Pipeline effects** — the ordered dispatch / complete / release
   effect trace of every function, from introspect's
   `ENGINE_DISPATCH_EFFECTS` / `STEP_COMPLETE_CALLS` /
   `ALLOCATOR_RELEASE_EFFECTS` tables (the ENGINE_STEP_DONATION
   precedent: the engine declares its effect surfaces, the analyzer
   reads them). Module-local calls are spliced into the caller's
   trace, loop bodies replay twice (loop-carried dispatches — the
   depth-2 pipe shape), so TPU203 can walk "is an allocator release
   reachable between a dispatch and its completion" per function.

Everything is name-based and module-local, like tpu-lint: locks are
keyed by their attribute/global NAME (one lock reached through two
names reads as two locks), threads crossing module boundaries are
invisible, and a lock held by a CALLER is invisible unless the access
line says `# guarded-by: <lock>`. The effect walk models `if` as a
fork: each arm starts from the pre-branch state, the merge is
pessimistic (a dispatch left outstanding on EITHER arm stays
outstanding), and an arm that ends in return/raise/break/continue
contributes nothing to the fall-through state — so an early-return
guard (`if x is None: return`) is the complete-guard idiom the
analyzer understands, while a wrapping `if x is not None: wait(x)`
reads as "may not complete". DESIGN_DECISIONS r22 records the full
false-negative boundary.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass

from paddle_tpu.jit import introspect as I

from ..engine import ModuleAnalysis

#: `# guarded-by: _lock` — asserts the named lock is held by every
#: caller when this line executes; the analyzer treats accesses on the
#: line as performed under that lock.
_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")

#: Method calls that mutate their receiver in place — a
#: `self._ring.append(...)` is a WRITE to `_ring` for lock-discipline
#: purposes.
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "add", "insert",
    "remove", "discard", "pop", "popleft", "popitem", "clear",
    "update", "setdefault", "put", "put_nowait", "sort", "reverse",
})

#: Constructors whose instances synchronize internally — accesses to
#: an attribute assigned from one of these are exempt from the shared
#: -mutable rules (queue/Event/lock objects guard themselves;
#: threading.local confines by construction).
_SYNCHRONIZED_TYPES = frozenset(
    I.BLOCKING_RECEIVER_TYPES
    + I.THREAD_LOCAL_CONSTRUCTORS
    + I.LOCK_CONSTRUCTORS
)

#: Constructor/initializer method names whose writes are
#: pre-concurrency by convention (no helper thread exists yet).
CTOR_NAMES = frozenset({"__init__", "__new__", "__post_init__"})


def _diverges(stmts):
    """True when a statement list ends by leaving the enclosing path
    (return/raise/break/continue) — such a branch contributes nothing
    to the fall-through state at an effect-walk merge point."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


@dataclass
class Access:
    key: tuple          # ("self", class_name, attr) | ("global", name)
    kind: str           # "read" | "write"
    node: object
    fi: object
    locks: frozenset    # lock names held (incl. guarded-by asserts)
    in_thread: bool

    def name(self):
        return f"self.{self.key[2]}" if self.key[0] == "self" \
            else self.key[1]


class RaceModuleAnalysis(ModuleAnalysis):
    """ModuleAnalysis + the concurrency fact tables above."""

    def __init__(self, path, src, module_name=None):
        super().__init__(path, src, module_name=module_name)
        self.guard_annotations = self._parse_guards(src)
        self._release_attrs = frozenset(
            a for attrs in sorted(I.ALLOCATOR_RELEASE_EFFECTS.values())
            for a in attrs)
        self._dispatch_attrs = frozenset(I.ENGINE_DISPATCH_EFFECTS)
        self._complete_calls = frozenset(I.STEP_COMPLETE_CALLS)
        self._collect_name_types()
        self._collect_thread_reachable()
        self.accesses = []
        self.blocking_under_lock = []  # (node, fi, lock, what)
        self.spawn_sites = []          # (node, fi) — thread starts
        self.effects = {}              # id(fi) -> [(kind, node, detail)]
        self._effect_memo = {}
        for fi in self.functions:
            _FnWalker(self, fi).run()

    # -- source annotations ------------------------------------------------

    @staticmethod
    def _parse_guards(src):
        out = {}
        for n, text in enumerate(src.splitlines(), start=1):
            m = _GUARD_RE.search(text)
            if m:
                out[n] = m.group(1)
        return out

    # -- lock / synchronized / mutable-global name tables ------------------

    @staticmethod
    def _binding_name(target):
        """Leaf name a lock/local/queue binding lives under: `x`,
        `self.x`, or the dict in `LOCKS[k] = threading.Lock()`."""
        if isinstance(target, ast.Name):
            return target.id
        if isinstance(target, ast.Attribute):
            return target.attr
        if isinstance(target, ast.Subscript):
            return RaceModuleAnalysis._binding_name(target.value)
        return None

    def _collect_name_types(self):
        self.lock_names = set()
        self.threadlocal_names = set()
        self.sync_names = set()
        self.name_types = {}       # leaf name -> set of canonical ctors
        self.mutable_globals = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = getattr(node, "value", None)
            if value is None:
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            names = sorted(n for n in (self._binding_name(t)
                                       for t in targets) if n)
            ctor = self.resolve(value.func) \
                if isinstance(value, ast.Call) else None
            leaf = value.id if isinstance(value, ast.Name) else (
                value.attr if isinstance(value, ast.Attribute) else None)
            for name in names:
                if ctor:
                    self.name_types.setdefault(name, set()).add(ctor)
                if ctor in I.LOCK_CONSTRUCTORS or (
                        leaf is not None and "lock" in leaf.lower()):
                    self.lock_names.add(name)
                if ctor in I.THREAD_LOCAL_CONSTRUCTORS:
                    self.threadlocal_names.add(name)
                if ctor in _SYNCHRONIZED_TYPES:
                    self.sync_names.add(name)
        # module-level mutable bindings (for global-write tracking)
        for node in self.module_fn.nodes:
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        self.mutable_globals.add(t.id)

    # -- thread escape -----------------------------------------------------

    def _collect_thread_reachable(self):
        self.thread_reachable = set()   # id(FuncInfo)
        self._thread_work = []

        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            owner = getattr(node, "_tl_owner", self.module_fn)
            fname = self.resolve(node.func)
            spec = I.THREAD_SPAWN_CALLS.get(fname)
            if spec is not None:
                kw_name, pos = spec
                target = None
                for kw in node.keywords:
                    if kw.arg == kw_name:
                        target = kw.value
                if target is None and len(node.args) > pos:
                    target = node.args[pos]
                if target is not None:
                    self._seed_thread_callable(target, owner)
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in I.EXECUTOR_SUBMIT_METHODS and \
                    node.args:
                self._seed_thread_callable(node.args[0], owner)

        # propagation: module-local callees of thread code run on the
        # thread too (pass-C shape of the traced-ness fixpoint)
        while self._thread_work:
            fi = self._thread_work.pop()
            for node in fi.nodes:
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Name):
                    callee = fi.lookup(f.id)
                    if callee is not None:
                        self._mark_thread(callee)
                elif isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id in ("self", "cls") and fi.class_name:
                    for cand in self._by_simple_name.get(f.attr, []):
                        if cand.class_name == fi.class_name:
                            self._mark_thread(cand)

    def _mark_thread(self, fi):
        if fi is None or id(fi) in self.thread_reachable:
            return
        self.thread_reachable.add(id(fi))
        self._thread_work.append(fi)

    def _seed_thread_callable(self, expr, owner):
        if isinstance(expr, ast.Name):
            self._mark_thread(owner.lookup(expr.id))
        elif isinstance(expr, ast.Lambda):
            self._mark_thread(getattr(expr, "_tl_func", None))
        elif isinstance(expr, ast.Attribute):
            cands = self._by_simple_name.get(expr.attr, [])
            for c in [c for c in cands if c.class_name] or cands:
                self._mark_thread(c)

    def is_thread_reachable(self, fi):
        return id(fi) in self.thread_reachable

    # -- effect sequences (TPU203) -----------------------------------------

    def effect_seq(self, fi, _stack=None):
        """Flattened ordered effect trace of `fi`: module-local calls
        inlined (effects re-anchored at the call site in `fi`), cycles
        cut. Entries are (kind, node, detail) with kind in
        dispatch/complete/release plus the structural fork/alt/join
        markers (always balanced; `detail` on alt/join is the
        diverged flag of the arm just closed)."""
        if id(fi) in self._effect_memo:
            return self._effect_memo[id(fi)]
        stack = _stack if _stack is not None else set()
        if id(fi) in stack:
            return []
        stack.add(id(fi))
        out = []
        for kind, node, detail in self.effects.get(id(fi), []):
            if kind == "call":
                for k2, _n2, d2 in self.effect_seq(detail, stack):
                    out.append((k2, node, d2))
            else:
                out.append((kind, node, detail))
        stack.discard(id(fi))
        if not stack:
            self._effect_memo[id(fi)] = out
        return out


class _FnWalker:
    """One function's lexical walk: lock-region stack, access
    recording, blocking-call sites, and the raw effect list."""

    def __init__(self, race, fi):
        self.r = race
        self.fi = fi
        self.held = []                 # stack of held lock names
        self.in_thread = race.is_thread_reachable(fi)
        self.effects = []
        self._seen_access = {}         # id(node) -> Access (replay dedupe)
        self._seen_blocking = set()

    def run(self):
        node = self.fi.node
        if isinstance(node, ast.Lambda):
            self.scan(node.body)
        else:
            self.block(getattr(node, "body", []))
        self.r.effects[id(self.fi)] = self.effects

    # -- statements --------------------------------------------------------

    def block(self, stmts):
        for s in stmts:
            self.stmt(s)

    def stmt(self, s):
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return                     # separate FuncInfo walks it
        if isinstance(s, ast.ClassDef):
            self.block(s.body)
            return
        if isinstance(s, ast.Assign):
            self.scan(s.value)
            for t in s.targets:
                self.write_target(t)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self.scan(s.value)
                self.write_target(s.target)
        elif isinstance(s, ast.AugAssign):
            self.scan(s.value)
            self.scan(s.target)        # read half of the update
            self.write_target(s.target)
        elif isinstance(s, ast.Expr):
            self.scan(s.value)
        elif isinstance(s, (ast.Return, ast.Raise, ast.Assert,
                            ast.Await)):
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self.scan(child)
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                self.write_target(t)
        elif isinstance(s, ast.If):
            # exclusive arms: fork the TPU203 state machine so a
            # dispatch on one arm can't read as "outstanding" across
            # the other, and a diverging arm (return/raise/...) drops
            # out of the fall-through merge entirely
            self.scan(s.test)
            self.effects.append(("fork", s, None))
            self.block(s.body)
            self.effects.append(("alt", s, _diverges(s.body)))
            self.block(s.orelse)
            self.effects.append(("join", s, _diverges(s.orelse)))
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            self.scan(s.iter)
            # replay the body: loop-carried dispatch/release ordering
            # (iteration N dispatches, N+1 releases) needs two passes
            self.block(s.body)
            self.block(s.body)
            self.block(s.orelse)
        elif isinstance(s, ast.While):
            self.scan(s.test)
            self.block(s.body)
            self.block(s.body)
            self.block(s.orelse)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in s.items:
                self.scan(item.context_expr)
                lock = self.lock_leaf(item.context_expr)
                if lock is not None:
                    self.held.append(lock)
                    pushed += 1
            self.block(s.body)
            for _ in range(pushed):
                self.held.pop()
        elif isinstance(s, ast.Try):
            self.block(s.body)
            for h in s.handlers:
                # each handler is an OPTIONAL branch off the main
                # line (first arm = "no exception", no effects)
                self.effects.append(("fork", h, None))
                self.effects.append(("alt", h, False))
                self.block(h.body)
                self.effects.append(("join", h, _diverges(h.body)))
            self.block(s.orelse)
            self.block(s.finalbody)
        else:
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self.scan(child)

    def lock_leaf(self, expr):
        """Lock name a `with <expr>:` guards, or None."""
        if isinstance(expr, ast.Name):
            return expr.id if expr.id in self.r.lock_names else None
        if isinstance(expr, ast.Attribute):
            return expr.attr if expr.attr in self.r.lock_names else None
        if isinstance(expr, ast.Subscript):
            return self.lock_leaf(expr.value)
        if isinstance(expr, ast.Call) and \
                isinstance(expr.func, ast.Name) and \
                expr.func.id == "getattr" and len(expr.args) >= 2 and \
                isinstance(expr.args[1], ast.Constant) and \
                isinstance(expr.args[1].value, str):
            # `with getattr(self, "_lock", threading.Lock()):` — the
            # defensive-attribute idiom still names the lock
            name = expr.args[1].value
            return name if name in self.r.lock_names else None
        return None

    # -- expressions -------------------------------------------------------

    def scan(self, e):
        if e is None or isinstance(e, ast.Lambda):
            return                     # lambda body is its own walk
        if isinstance(e, ast.Call):
            if isinstance(e.func, ast.Attribute):
                self.scan(e.func.value)
            for a in e.args:
                self.scan(a)
            for kw in e.keywords:
                self.scan(kw.value)
            self.handle_call(e)
            return
        if isinstance(e, ast.Attribute):
            self.record(e, "write" if isinstance(e.ctx, (ast.Store,
                                                         ast.Del))
                        else "read")
            self.scan(e.value)
            return
        if isinstance(e, ast.Name):
            if isinstance(e.ctx, ast.Load):
                self.record(e, "read")
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self.scan(child)
            elif isinstance(child, ast.comprehension):
                self.scan(child.iter)
                for cond in child.ifs:
                    self.scan(cond)
            elif isinstance(child, ast.keyword):
                self.scan(child.value)

    def write_target(self, t):
        if isinstance(t, ast.Attribute):
            self.record(t, "write")
            self.scan(t.value)
        elif isinstance(t, ast.Subscript):
            # self._slots[i] = x / _STATE[k] = x: write to the container
            self.record(t.value, "write")
            self.scan(t.value)
            self.scan(t.slice)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self.write_target(e)
        elif isinstance(t, ast.Starred):
            self.write_target(t.value)
        elif isinstance(t, ast.Name):
            if t.id in self.fi.global_names:
                self.record(t, "write")

    # -- access recording --------------------------------------------------

    def locks_at(self, node):
        held = set(self.held)
        guard = self.r.guard_annotations.get(
            getattr(node, "lineno", 0))
        if guard is not None:
            held.add(guard)
        return frozenset(held)

    def key_of(self, node):
        """Shared-state key of an access, or None for locals /
        synchronized / thread-confined storage."""
        r = self.r
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and base.id in ("self",
                                                          "cls"):
                attr = node.attr
                if attr in r.sync_names or attr in r.lock_names:
                    return None
                return ("self", self.fi.class_name or "", attr)
            if isinstance(base, ast.Attribute):
                # self._tls.acc: thread-local base confines the leaf
                if isinstance(base.value, ast.Name) and \
                        base.value.id in ("self", "cls") and \
                        base.attr in r.threadlocal_names:
                    return None
            return None
        if isinstance(node, ast.Name):
            name = node.id
            if name in self.fi.global_names or (
                    name in r.mutable_globals
                    and name not in self.fi.local_bindings
                    and self.fi is not r.module_fn):
                if name in r.sync_names or name in r.lock_names or \
                        name in r.threadlocal_names:
                    return None
                return ("global", name)
        return None

    def record(self, node, kind):
        key = self.key_of(node)
        if key is None:
            return
        prev = self._seen_access.get(id(node))
        if prev is not None:
            # `self._counts[i] += 1`: the expression scan sees the
            # inner Attribute as a Load first, then write_target
            # reports the same node as the store — upgrade, the
            # write is what lock discipline cares about
            if kind == "write" and prev.kind == "read":
                prev.kind = "write"
            return
        acc = Access(
            key=key, kind=kind, node=node, fi=self.fi,
            locks=self.locks_at(node), in_thread=self.in_thread)
        self._seen_access[id(node)] = acc
        self.r.accesses.append(acc)

    # -- calls: effects, blocking, mutators, spawns ------------------------

    def handle_call(self, c):
        r = self.r
        fname = r.resolve(c.func)
        attr = c.func.attr if isinstance(c.func, ast.Attribute) \
            else None

        # mutator methods write their receiver
        if attr in _MUTATOR_METHODS and \
                isinstance(c.func.value, (ast.Attribute, ast.Name)):
            self.record(c.func.value, "write")

        # thread spawns (TPU205 checks these against jit-reachability)
        if fname in I.THREAD_SPAWN_CALLS or (
                attr in I.EXECUTOR_SUBMIT_METHODS and c.args):
            r.spawn_sites.append((c, self.fi))

        # blocking call under a held lock (TPU204)
        what = None
        if fname in I.BLOCKING_CALLS:
            what = fname
        elif attr in I.BLOCKING_METHODS and \
                self._blocking_receiver(c.func.value):
            what = f".{attr}()"
        locks = self.locks_at(c)
        if what is not None and locks and id(c) not in \
                self._seen_blocking:
            self._seen_blocking.add(id(c))
            r.blocking_under_lock.append(
                (c, self.fi, sorted(locks)[0], what))

        # pipeline effects (TPU203)
        if fname in r._complete_calls:
            self.effects.append(("complete", c, fname))
        elif attr in r._dispatch_attrs:
            self.effects.append(("dispatch", c, attr))
        elif attr in r._release_attrs and \
                self.lock_leaf(c.func.value) is None:
            self.effects.append(("release", c, attr))
        else:
            callee = self._local_callee(c)
            if callee is not None:
                self.effects.append(("call", c, callee))

    def _blocking_receiver(self, base):
        """Was the receiver built by a known blocking type (Thread,
        Event, queue, lock)? Gates `.join()`/`.get()`/`.wait()` so
        `",".join(...)` and `dict.get` stay invisible."""
        r = self.r
        types = set()
        if isinstance(base, ast.Name):
            call, _scope = self.fi.lookup_assigned_call(base.id)
            if call is not None:
                ctor = r.resolve(call.func)
                if ctor:
                    types.add(ctor)
            types |= r.name_types.get(base.id, set()) \
                if base.id in r.mutable_globals else set()
        elif isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id in ("self", "cls"):
            types |= r.name_types.get(base.attr, set())
        return bool(types & set(I.BLOCKING_RECEIVER_TYPES))

    def _local_callee(self, c):
        f = c.func
        if isinstance(f, ast.Name):
            return self.fi.lookup(f.id)
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and \
                f.value.id in ("self", "cls") and self.fi.class_name:
            for cand in self.r._by_simple_name.get(f.attr, []):
                if cand.class_name == self.fi.class_name:
                    return cand
        return None
