"""tpu-race rules (TPU2xx): lock discipline + allocator lifetime.

Each check takes a `RaceModuleAnalysis` and returns Finding objects.
The TPU2xx namespace sits beside tpu-lint's TPU0xx (AST trace-safety)
and tpu-verify's TPU1xx (jaxpr contracts); a registry test asserts the
three stay disjoint.
"""
from __future__ import annotations

import ast

from paddle_tpu.jit import introspect as I

from .model import CTOR_NAMES


def _grouped(mod):
    """accesses grouped by shared-state key, deterministic order."""
    groups = {}
    for a in mod.accesses:
        groups.setdefault(a.key, []).append(a)
    return sorted(groups.items())


def _line(a):
    return getattr(a.node, "lineno", 0)


def check_tpu201(mod):
    """unguarded-shared-mutable: an attribute/global written by
    helper-thread-reachable code with NO lock held (and no guarded-by
    assertion, no threading.local confinement) while step-thread code
    also touches it."""
    if not mod.thread_reachable:
        return []
    findings = []
    for key, accs in _grouped(mod):
        thread_writes = sorted(
            (a for a in accs if a.in_thread and a.kind == "write"
             and not a.locks), key=_line)
        if not thread_writes:
            continue
        step_side = sorted(
            (a for a in accs if not a.in_thread
             and a.fi.name not in CTOR_NAMES), key=_line)
        if not step_side:
            continue
        touch = step_side[0]
        for a in thread_writes:
            findings.append(mod.finding(
                "TPU201", a.node,
                f"{a.name()} is written on a helper thread with no "
                f"lock held, but the step thread touches it too "
                f"(line {_line(touch)}); hold one common lock on both "
                "sides, confine it via threading.local, or assert the "
                "caller's lock with '# guarded-by: <lock>'", a.fi))
    return findings


def check_tpu202(mod):
    """inconsistent-guard: one attribute written under a lock in one
    place and with no lock (or a different lock) in another. Unlocked
    thread-side writes are TPU201's domain and skipped here; reads
    are deliberately out of scope (racy snapshot reads are a
    documented idiom — see the metrics `.value` properties)."""
    findings = []
    for key, accs in _grouped(mod):
        writes = sorted((a for a in accs if a.kind == "write"
                         and a.fi.name not in CTOR_NAMES), key=_line)
        locked = [a for a in writes if a.locks]
        if not locked:
            continue
        primary = sorted(locked[0].locks)[0]
        for a in writes:
            if a.locks and primary in a.locks:
                continue
            if a.locks:
                other = sorted(a.locks)[0]
                msg = (f"{a.name()} is written under lock '{other}' "
                       f"here but under '{primary}' at line "
                       f"{_line(locked[0])} — one attribute, one lock")
            else:
                if a.in_thread and mod.thread_reachable:
                    continue           # TPU201 reports that shape
                msg = (f"{a.name()} is written under lock '{primary}' "
                       f"at line {_line(locked[0])} but with no lock "
                       "here; hold the same lock or assert the "
                       "caller's with '# guarded-by: <lock>'")
            findings.append(mod.finding("TPU202", a.node, msg, a.fi))
    return findings


def check_tpu203(mod):
    """free-before-complete: an allocator release (introspect
    ALLOCATOR_RELEASE_EFFECTS) reachable on a path between a recorded
    dispatch (ENGINE_DISPATCH_EFFECTS) and its completion
    (STEP_COMPLETE_CALLS) — the zombie-write hazard that holds the
    async pipe at depth 1 (DESIGN_DECISIONS r21/r22). Loop bodies
    replay twice in the effect walk, so the depth-2 shape (iteration
    N+1 frees before waiting on iteration N's dispatch) fires too.

    `if` arms fork the outstanding-dispatch state (exclusive arms
    can't see each other's dispatches); the merge is pessimistic —
    a dispatch surviving on ANY non-diverging arm stays outstanding,
    and an arm ending in return/raise/break/continue drops out of
    the merge entirely (early-return guards read as guards)."""
    findings = []
    seen = set()
    for fi in mod.functions:
        outstanding = None
        forks = []      # [saved_state, [non-diverged arm exit states]]
        for kind, node, detail in mod.effect_seq(fi):
            if kind == "dispatch":
                outstanding = node
            elif kind == "complete":
                outstanding = None
            elif kind == "fork":
                forks.append([outstanding, []])
            elif kind == "alt":
                if forks:
                    saved, rec = forks[-1]
                    if not detail:
                        rec.append(outstanding)
                    outstanding = saved
            elif kind == "join":
                if forks:
                    saved, rec = forks.pop()
                    if not detail:
                        rec.append(outstanding)
                    merged = None
                    for st in rec:
                        if st is not None:
                            merged = st
                    outstanding = merged if rec else saved
            elif kind == "release" and outstanding is not None:
                if outstanding is node:
                    # dispatch and release both spliced from ONE
                    # callee: reported inside that callee, not here
                    continue
                sig = (id(fi), getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), detail)
                if sig in seen:
                    continue
                seen.add(sig)
                findings.append(mod.finding(
                    "TPU203", node,
                    f"allocator release '{detail}' is reachable "
                    f"between the dispatch at line "
                    f"{getattr(outstanding, 'lineno', 0)} and its "
                    "completion — a dispatched step may still write "
                    "the released blocks (zombie write); complete "
                    "the in-flight step before releasing", fi))
    return findings


def check_tpu204(mod):
    """blocking-call-under-lock: block_until_ready / Thread.join /
    sleep / queue-get while holding a registry or allocator lock —
    every other thread contending on that lock stalls behind device
    or wall-clock time."""
    findings = []
    for node, fi, lock, what in mod.blocking_under_lock:
        findings.append(mod.finding(
            "TPU204", node,
            f"blocking call {what} while holding lock '{lock}'; "
            "move the wait outside the guarded region", fi))
    return findings


def check_tpu205(mod):
    """thread-spawn-in-trace: jit-reachable code starting threads
    (tpu-lint's reachability tables) — a spawn inside a traced
    function runs ONCE at trace time and stages nothing."""
    findings = []
    for node, fi in mod.spawn_sites:
        if not fi.traced:
            continue
        fname = mod.resolve(node.func)
        what = fname if fname in I.THREAD_SPAWN_CALLS \
            else f".{node.func.attr}(...)" \
            if isinstance(node.func, ast.Attribute) else "thread spawn"
        findings.append(mod.finding(
            "TPU205", node,
            f"jit-reachable code starts a thread ({what}); the spawn "
            "runs once at trace time and is invisible to the compiled "
            "program — hoist it out of the traced region", fi))
    return findings


#: rule id -> (name, description, check). TPU200 is the parse-error
#: rule (no checker — emitted by analyze_file), mirroring TPU000.
RACE_RULES = {
    "TPU200": ("parse-error",
               "file could not be parsed (reported, never skipped)",
               None),
    "TPU201": ("unguarded-shared-mutable",
               "helper-thread write to shared state with no common "
               "lock, confinement, or guarded-by annotation",
               check_tpu201),
    "TPU202": ("inconsistent-guard",
               "attribute written under different locks, or both "
               "with and without one",
               check_tpu202),
    "TPU203": ("free-before-complete",
               "allocator release between a dispatched step and its "
               "completion (zombie-write hazard)",
               check_tpu203),
    "TPU204": ("blocking-call-under-lock",
               "block_until_ready/join/sleep/queue-get while holding "
               "a lock",
               check_tpu204),
    "TPU205": ("thread-spawn-in-trace",
               "jit-reachable code starts a thread",
               check_tpu205),
}


def all_race_rule_ids():
    return sorted(RACE_RULES)
