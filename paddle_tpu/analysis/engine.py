"""tpu-lint analysis engine: per-module AST model.

Three layers feed the rules:

1. **Alias resolution** — every `import`/`from ... import` binds a
   local name to a canonical dotted path, so `jnp.matmul`,
   `jax.numpy.matmul` and `from jax.numpy import matmul` all resolve
   to ``jax.numpy.matmul`` before any registry lookup
   (`paddle_tpu.jit.introspect` holds the registries — the jit
   layer's own metadata, not string patterns in the analyzer).

2. **Traced-ness fixpoint** — a function is traced if it is (a)
   decorated by a trace entry (`@jax.jit`, `@to_static`,
   `@partial(jax.jit, ...)`), (b) passed at a traced-callable
   position of a tracing API (`jax.jit(f)`, `lax.scan(body, ...)`,
   `pallas_call(kernel, ...)`), (c) RETURNED by a local builder whose
   result is staged (`jax.jit(self._make_step_fn())` marks the
   nested ``step_fn`` that the builder chain returns), or (d) called
   from a traced function — including calls through a local variable
   bound to a builder's result (``forward_loss = make_forward_loss(...)``
   then ``forward_loss(...)`` inside a traced body). All resolution is
   name-based and module-local; the false-negative boundary is
   documented in DESIGN_DECISIONS.

3. **Taint** — inside a traced function, which expressions derive
   from traced operands: parameters seed the taint set (minus
   `self`/`cls`, minus params at `static_argnums`/`static_argnames`
   of the staging call, minus params with python-constant defaults —
   those are near-always intended static) and taint propagates
   through arithmetic, `jnp.*`/`jax.*` results, subscripts and
   assignments. Shape/dtype/ndim reads, `len()`, identity
   comparisons and `isinstance` are concrete under trace and
   untaint.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

from paddle_tpu.jit import introspect as I

from .findings import Finding

UNTAINT_ATTRS = {"shape", "dtype", "ndim", "size", "name", "sharding",
                 "weak_type"}
UNTAINT_CALLS = {"len", "isinstance", "hasattr", "callable", "type",
                 "id", "range", "repr", "str", "format", "getattr"}


@dataclass
class FuncInfo:
    node: object
    name: str
    qualname: str
    parent: "FuncInfo | None"
    class_name: str | None = None
    is_lambda: bool = False
    params: list = field(default_factory=list)
    param_defaults: dict = field(default_factory=dict)  # name -> has const default
    static_params: set = field(default_factory=set)
    traced: bool = False
    trace_via: str | None = None
    dy2static: bool = False
    not_traced: bool = False
    has_bf16: bool = False
    children: dict = field(default_factory=dict)   # simple name -> FuncInfo
    lambdas: list = field(default_factory=list)
    nodes: list = field(default_factory=list)      # ast nodes owned directly
    returns: list = field(default_factory=list)    # owned Return.value exprs
    local_bindings: set = field(default_factory=set)
    assigns_from_calls: dict = field(default_factory=dict)  # name -> Call
    global_names: set = field(default_factory=set)
    taint: set | None = None

    def effective_bf16(self):
        fi = self
        while fi is not None:
            if fi.has_bf16:
                return True
            fi = fi.parent
        return False

    def lookup(self, name):
        """Resolve a simple name to a FuncInfo through the scope chain."""
        fi = self
        while fi is not None:
            if name in fi.children:
                return fi.children[name]
            fi = fi.parent
        return None

    def lookup_assigned_call(self, name):
        fi = self
        while fi is not None:
            if name in fi.assigns_from_calls:
                return fi.assigns_from_calls[name], fi
            fi = fi.parent
        return None, None


class ModuleAnalysis:
    def __init__(self, path, src, module_name=None):
        self.path = path
        self.src = src
        self.module_name = module_name or ""
        self.tree = ast.parse(src, filename=path)
        self.aliases = {}
        self.module_fn = FuncInfo(node=self.tree, name="<module>",
                                  qualname="<module>", parent=None)
        self.functions = [self.module_fn]   # all FuncInfos incl lambdas
        self._by_simple_name = {}
        self._collect_imports()
        self._build_function_table()
        self._compute_pure_predicates()
        self._resolve_tracedness()

    # -- alias / name resolution -------------------------------------------

    def _collect_imports(self):
        pkg_parts = self.module_name.split(".")[:-1] if self.module_name \
            else []
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
                    else:
                        head = a.name.split(".")[0]
                        self.aliases.setdefault(head, head)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base_parts = pkg_parts[:len(pkg_parts)
                                           - (node.level - 1)]
                    base = ".".join(base_parts)
                    mod = f"{base}.{node.module}" if node.module else base
                else:
                    mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    target = f"{mod}.{a.name}" if mod else a.name
                    self.aliases[a.asname or a.name] = target

    def resolve(self, node):
        """Canonical dotted name of an expression, or None."""
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value)
            return f"{base}.{node.attr}" if base else None
        return None

    # -- function table ----------------------------------------------------

    def _build_function_table(self):
        mod = self

        class Builder(ast.NodeVisitor):
            def __init__(self):
                self.owner = mod.module_fn
                self.class_stack = []

            def _register(self, fi):
                mod.functions.append(fi)
                mod._by_simple_name.setdefault(fi.name, []).append(fi)

            def _func(self, node, name, is_lambda=False):
                parent = self.owner
                qual = name if parent is mod.module_fn \
                    else f"{parent.qualname}.{name}"
                if self.class_stack and parent is mod.module_fn:
                    qual = f"{'.'.join(self.class_stack)}.{name}"
                fi = FuncInfo(node=node, name=name, qualname=qual,
                              parent=parent, is_lambda=is_lambda,
                              class_name=self.class_stack[-1]
                              if self.class_stack else None)
                args = node.args
                all_args = (list(getattr(args, "posonlyargs", []))
                            + list(args.args) + list(args.kwonlyargs))
                fi.params = [a.arg for a in all_args]
                if args.vararg:
                    fi.params.append(args.vararg.arg)
                if args.kwarg:
                    fi.params.append(args.kwarg.arg)
                defaults = list(args.defaults)
                for a, d in zip(reversed(args.args), reversed(defaults)):
                    fi.param_defaults[a.arg] = isinstance(d, ast.Constant)
                for a, d in zip(args.kwonlyargs, args.kw_defaults):
                    if d is not None:
                        fi.param_defaults[a.arg] = isinstance(d, ast.Constant)
                fi.local_bindings = set(fi.params)
                self._register(fi)
                if is_lambda:
                    parent.lambdas.append(fi)
                else:
                    parent.children[name] = fi
                return fi

            def visit_ClassDef(self, node):
                node._tl_owner = self.owner
                self.owner.nodes.append(node)
                self.class_stack.append(node.name)
                for child in ast.iter_child_nodes(node):
                    self.visit(child)
                self.class_stack.pop()

            def _visit_func(self, node, fi):
                prev, self.owner = self.owner, fi
                prev_cls, self.class_stack = self.class_stack, []
                body = node.body if isinstance(node.body, list) \
                    else [node.body]
                for child in body:
                    self.visit(child)
                # decorators/defaults are evaluated in the ENCLOSING scope
                self.owner, self.class_stack = prev, prev_cls
                for d in getattr(node, "decorator_list", []):
                    self.visit(d)
                for d in (node.args.defaults
                          + [x for x in node.args.kw_defaults if x]):
                    self.visit(d)

            def visit_FunctionDef(self, node):
                node._tl_owner = self.owner
                self.owner.nodes.append(node)
                fi = self._func(node, node.name)
                self._visit_func(node, fi)

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                node._tl_owner = self.owner
                # occurrence index, NOT lineno: finding IDs hash the
                # qualname and must survive line shifts
                fi = self._func(node,
                                f"<lambda#{len(self.owner.lambdas)}>",
                                is_lambda=True)
                node._tl_func = fi
                self._visit_func(node, fi)

            def generic_visit(self, node):
                node._tl_owner = self.owner
                self.owner.nodes.append(node)
                super().generic_visit(node)

        b = Builder()
        for child in ast.iter_child_nodes(self.tree):
            b.visit(child)

        # per-owner bookkeeping: bindings, returns, builder assigns, bf16
        self._self_attr_assigns = {}
        for fi in self.functions:
            for node in fi.nodes:
                if isinstance(node, ast.Return) and node.value is not None:
                    fi.returns.append(node.value)
                elif isinstance(node, ast.Global):
                    fi.global_names.update(node.names)
                elif isinstance(node, (ast.Assign, ast.AnnAssign,
                                       ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        for n in self._target_names(t):
                            fi.local_bindings.add(n)
                    value = getattr(node, "value", None)
                    if isinstance(node, ast.Assign) and \
                            isinstance(value, ast.Call) and \
                            len(node.targets) == 1 and \
                            isinstance(node.targets[0], ast.Name):
                        fi.assigns_from_calls[node.targets[0].id] = value
                    if isinstance(node, ast.Assign) and value is not None:
                        for t in node.targets:
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id in ("self", "cls"):
                                self._self_attr_assigns.setdefault(
                                    t.attr, []).append((value, fi))
                elif isinstance(node, ast.For):
                    for n in self._target_names(node.target):
                        fi.local_bindings.add(n)
                elif isinstance(node, ast.With):
                    for item in node.items:
                        if item.optional_vars is not None:
                            for n in self._target_names(item.optional_vars):
                                fi.local_bindings.add(n)
                elif isinstance(node, (ast.Import, ast.ImportFrom)):
                    for a in node.names:
                        fi.local_bindings.add(
                            (a.asname or a.name).split(".")[0])
                elif isinstance(node, ast.comprehension):
                    for n in self._target_names(node.target):
                        fi.local_bindings.add(n)
                elif isinstance(node, ast.ExceptHandler) and node.name:
                    fi.local_bindings.add(node.name)
                if isinstance(node, ast.Attribute) and \
                        node.attr == "bfloat16":
                    fi.has_bf16 = True
                elif isinstance(node, ast.Constant) and \
                        node.value == "bfloat16":
                    fi.has_bf16 = True

    @staticmethod
    def _target_names(target):
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            out = []
            for e in target.elts:
                out.extend(ModuleAnalysis._target_names(e))
            return out
        if isinstance(target, ast.Starred):
            return ModuleAnalysis._target_names(target.value)
        return []

    # -- pure predicates ---------------------------------------------------

    _PREDICATE_CALLS = {"isinstance", "issubclass", "hasattr", "callable",
                        "type", "len", "getattr"}

    def _compute_pure_predicates(self):
        """Simple names of local functions whose entire body is one
        `return <structure test>` — isinstance/hasattr chains over
        their arguments. Such calls answer python-level questions and
        never depend on a tracer's VALUE, so they untaint."""

        def pure(e):
            if isinstance(e, (ast.Name, ast.Constant, ast.Attribute)):
                return True
            if isinstance(e, ast.Tuple):
                return all(pure(x) for x in e.elts)
            if isinstance(e, ast.BoolOp):
                return all(pure(v) for v in e.values)
            if isinstance(e, ast.UnaryOp):
                return pure(e.operand)
            if isinstance(e, ast.Compare):
                return all(isinstance(op, (ast.Is, ast.IsNot))
                           for op in e.ops) and pure(e.left) and \
                    all(pure(c) for c in e.comparators)
            if isinstance(e, ast.Call):
                return self.resolve(e.func) in self._PREDICATE_CALLS \
                    and all(pure(a) for a in e.args)
            return False

        self.pure_predicates = set()
        for fi in self.functions:
            if fi.is_lambda or fi.node is self.tree:
                continue
            body = [s for s in fi.node.body
                    if not (isinstance(s, ast.Expr)
                            and isinstance(s.value, ast.Constant))]
            if len(body) == 1 and isinstance(body[0], ast.Return) and \
                    body[0].value is not None and pure(body[0].value):
                self.pure_predicates.add(fi.name)

    # -- traced-ness -------------------------------------------------------

    def _jit_kwargs(self, call):
        """(static_param_positions, static_param_names) from a jit-like
        call's keywords — constant values only."""
        positions, names = set(), set()
        for kw in call.keywords:
            if kw.arg in I.STATIC_ARG_KEYWORDS:
                val = kw.value
                consts = []
                if isinstance(val, ast.Constant):
                    consts = [val.value]
                elif isinstance(val, (ast.Tuple, ast.List)):
                    consts = [e.value for e in val.elts
                              if isinstance(e, ast.Constant)]
                for c in consts:
                    if isinstance(c, int) and not isinstance(c, bool):
                        positions.add(c)
                    elif isinstance(c, str):
                        names.add(c)
        return positions, names

    def _mark_traced(self, fi, via, static_info=None):
        if fi is None or fi.traced or fi.not_traced:
            return
        fi.traced = True
        fi.trace_via = via
        fi.dy2static = I.TRACE_DECORATORS.get(via) == "dy2static"
        if static_info:
            positions, names = static_info
            offset = 1 if fi.params and fi.params[0] in ("self", "cls") \
                else 0
            for p in positions:
                idx = p + offset
                if 0 <= idx < len(fi.params):
                    fi.static_params.add(fi.params[idx])
            fi.static_params.update(n for n in names if n in fi.params)
        self._worklist.append(fi)

    def _stage_expr(self, expr, owner, via, static_info, depth=0,
                    visited=None):
        """An expression is being staged as a traced callable: resolve
        it to local FuncInfos (through builder returns, one module)."""
        if depth > 6:
            return
        visited = visited if visited is not None else set()
        if isinstance(expr, ast.Name):
            fi = owner.lookup(expr.id)
            if fi is not None:
                self._mark_traced(fi, via, static_info)
                return
            built, _scope = owner.lookup_assigned_call(expr.id)
            if built is not None and id(built) not in visited:
                # f = builder(...) ; jax.jit(f)
                visited.add(id(built))
                self._stage_expr(built, getattr(built, "_tl_owner",
                                                owner),
                                 via, static_info, depth + 1, visited)
        elif isinstance(expr, ast.Lambda):
            self._mark_traced(getattr(expr, "_tl_func", None), via,
                              static_info)
        elif isinstance(expr, (ast.Tuple, ast.List)):
            for e in expr.elts:
                self._stage_expr(e, owner, via, static_info, depth + 1,
                                 visited)
        elif isinstance(expr, ast.Attribute):
            # jax.jit(self._decode_pure): stage every rhs ever assigned
            # to that instance attribute in this module
            for rhs, rhs_owner in self._self_attr_assigns.get(
                    expr.attr, []):
                if id(rhs) not in visited:
                    visited.add(id(rhs))
                    self._stage_expr(rhs, rhs_owner, via, static_info,
                                     depth + 1, visited)
        elif isinstance(expr, ast.Call):
            fname = self.resolve(expr.func)
            if fname in I.PASSTHROUGH_WRAPPERS:
                # count_traces(f) / partial(f, ...): trace semantics
                # pass through to the first argument. Keywords bound by
                # partial are python constants at trace-build time —
                # static params of the staged function.
                if expr.args:
                    if fname in ("functools.partial", "partial"):
                        bound = {kw.arg for kw in expr.keywords if kw.arg}
                        positions, names = static_info or (set(), set())
                        static_info = (set(positions),
                                       set(names) | bound)
                    self._stage_expr(expr.args[0], owner, via,
                                     static_info, depth + 1, visited)
                return
            builders = []
            f = expr.func
            if isinstance(f, ast.Name):
                b = owner.lookup(f.id)
                if b is not None:
                    builders = [b]
            elif isinstance(f, ast.Attribute):
                # self._make_step_fn() — resolve the method by simple
                # name anywhere in the module (class-local preferred)
                cands = self._by_simple_name.get(f.attr, [])
                builders = [c for c in cands if c.class_name] or cands
            for b in builders:
                if id(b) in visited:
                    continue
                visited.add(id(b))
                for ret in b.returns:
                    self._stage_expr(ret, b, via, static_info, depth + 1,
                                     visited)

    def _resolve_tracedness(self):
        self._worklist = []
        # pass A: decorators
        for fi in self.functions:
            for dec in getattr(fi.node, "decorator_list", []):
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = self.resolve(target)
                if name in I.NOT_TRACED_DECORATORS:
                    fi.not_traced = True
                    continue
                static_info = self._jit_kwargs(dec) \
                    if isinstance(dec, ast.Call) else None
                if name in I.TRACE_DECORATORS:
                    self._mark_traced(fi, name, static_info)
                elif name in ("functools.partial", "partial") and \
                        isinstance(dec, ast.Call) and dec.args:
                    inner = self.resolve(dec.args[0])
                    if inner in I.TRACE_DECORATORS:
                        self._mark_traced(fi, inner, static_info)

        # pass B: call-site staging
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = self.resolve(node.func)
            positions = None
            if fname in I.TRACING_CALLABLES:
                positions = I.TRACING_CALLABLES[fname]
            elif fname in ("functools.partial", "partial") and node.args:
                inner = self.resolve(node.args[0])
                if inner in I.TRACING_CALLABLES:
                    # partial(jax.jit, static_argnums=...)(f) is rare;
                    # partial(fn) staged later by the outer call is the
                    # common shape — nothing to do here.
                    continue
            if positions is None:
                continue
            owner = getattr(node, "_tl_owner", self.module_fn)
            static_info = self._jit_kwargs(node) \
                if fname in I.JIT_LIKE else None
            for pos in positions:
                if pos < len(node.args):
                    self._stage_expr(node.args[pos], owner, fname,
                                     static_info)

        # pass C: propagation — callees of traced functions are traced
        while self._worklist:
            fi = self._worklist.pop()
            for child in list(fi.children.values()) + fi.lambdas:
                self._mark_traced(child, f"nested:{fi.qualname}")
            for node in fi.nodes:
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Name):
                    callee = fi.lookup(f.id)
                    if callee is not None:
                        self._mark_traced(
                            callee, f"called-from:{fi.qualname}")
                        continue
                    built, _scope = fi.lookup_assigned_call(f.id)
                    if built is not None:
                        # forward_loss = make_forward_loss(...) then
                        # forward_loss(...) under trace: the builder's
                        # returned functions run traced
                        self._stage_expr(
                            built, getattr(built, "_tl_owner", fi),
                            f"called-from:{fi.qualname}", None)
                elif isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id in ("self", "cls") and fi.class_name:
                    for cand in self._by_simple_name.get(f.attr, []):
                        if cand.class_name == fi.class_name:
                            self._mark_traced(
                                cand, f"called-from:{fi.qualname}")

    # -- taint -------------------------------------------------------------

    def func_taint(self, fi):
        """Names holding traced values inside a traced function
        (memoized; parents computed first so closures inherit)."""
        if fi.taint is not None:
            return fi.taint
        seed = set()
        if fi.traced:
            for p in fi.params:
                if p in ("self", "cls") or p in fi.static_params:
                    continue
                if fi.param_defaults.get(p):
                    continue  # constant-default params: near-always static
                seed.add(p)
        if fi.parent is not None and fi.parent.traced:
            # closures: names tainted in the enclosing traced scope stay
            # tainted here unless locally rebound (params shadow too)
            parent_taint = self.func_taint(fi.parent)
            seed |= {n for n in parent_taint
                     if n not in fi.local_bindings}
        fi.taint = seed
        # two forward passes: taint only grows, and the second pass
        # stabilizes loop-carried assignments
        for _ in range(2):
            for node in fi.nodes:
                self._taint_stmt(node, fi)
        return fi.taint

    def _taint_stmt(self, node, fi):
        if isinstance(node, ast.Assign):
            t = self.expr_taint(node.value, fi)
            if t:
                for tgt in node.targets:
                    fi.taint.update(self._target_names(tgt))
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if self.expr_taint(node.value, fi):
                fi.taint.update(self._target_names(node.target))
        elif isinstance(node, ast.AugAssign):
            if self.expr_taint(node.value, fi) or \
                    self.expr_taint(node.target, fi):
                fi.taint.update(self._target_names(node.target))
        elif isinstance(node, ast.For):
            if self.expr_taint(node.iter, fi):
                fi.taint.update(self._target_names(node.target))
        elif isinstance(node, ast.With):
            for item in node.items:
                if item.optional_vars is not None and \
                        self.expr_taint(item.context_expr, fi):
                    fi.taint.update(
                        self._target_names(item.optional_vars))
        elif isinstance(node, ast.comprehension):
            if self.expr_taint(node.iter, fi):
                fi.taint.update(self._target_names(node.target))

    def expr_taint(self, e, fi):
        """Whether an expression may hold a traced value."""
        taint = fi.taint if fi.taint is not None else set()
        if isinstance(e, ast.Name):
            return e.id in taint
        if isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Attribute):
            if e.attr in UNTAINT_ATTRS:
                return False
            return self.expr_taint(e.value, fi)
        if isinstance(e, ast.Call):
            fname = self.resolve(e.func)
            if fname in UNTAINT_CALLS:
                return False
            # local isinstance-style predicates (`_is_arraylike(x)`,
            # `_is_traced(x)`) answer python-structure questions, never
            # tracer values — calls to them are concrete under trace
            if isinstance(e.func, ast.Name) and \
                    e.func.id in self.pure_predicates:
                return False
            # NOTE: no blanket "jnp call => tainted": inside a trace,
            # jnp.zeros(...) etc. are constants; only values derived
            # from traced INPUTS are tracers, which argument
            # propagation below captures.
            if isinstance(e.func, ast.Attribute):
                if self.expr_taint(e.func.value, fi):
                    return True
            return any(self.expr_taint(a, fi) for a in e.args) or \
                any(self.expr_taint(kw.value, fi) for kw in e.keywords)
        if isinstance(e, ast.BinOp):
            return self.expr_taint(e.left, fi) or \
                self.expr_taint(e.right, fi)
        if isinstance(e, ast.UnaryOp):
            return self.expr_taint(e.operand, fi)
        if isinstance(e, ast.BoolOp):
            return any(self.expr_taint(v, fi) for v in e.values)
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in e.ops):
                return False
            return self.expr_taint(e.left, fi) or \
                any(self.expr_taint(c, fi) for c in e.comparators)
        if isinstance(e, ast.Subscript):
            return self.expr_taint(e.value, fi)
        if isinstance(e, ast.IfExp):
            return any(self.expr_taint(x, fi)
                       for x in (e.test, e.body, e.orelse))
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr_taint(x, fi) for x in e.elts)
        if isinstance(e, ast.Dict):
            return any(self.expr_taint(v, fi)
                       for v in e.values if v is not None)
        if isinstance(e, ast.JoinedStr):
            return any(self.expr_taint(v.value, fi)
                       for v in e.values
                       if isinstance(v, ast.FormattedValue))
        if isinstance(e, ast.Starred):
            return self.expr_taint(e.value, fi)
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return any(self.expr_taint(g.iter, fi) for g in e.generators)
        if isinstance(e, ast.DictComp):
            return any(self.expr_taint(g.iter, fi) for g in e.generators)
        return False

    # -- helpers for rules ---------------------------------------------------

    def finding(self, rule, node, message, fi=None):
        line = getattr(node, "lineno", 1)
        src_line = ""
        lines = self.src.splitlines()
        if 1 <= line <= len(lines):
            src_line = lines[line - 1].strip()
        return Finding(rule=rule, path=self.path, line=line,
                       col=getattr(node, "col_offset", 0),
                       message=message,
                       qualname=(fi or self.module_fn).qualname,
                       source=src_line)

    def traced_functions(self):
        return [fi for fi in self.functions if fi.traced]
