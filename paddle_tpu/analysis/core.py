"""tpu-lint — trace-safety & recompile-hazard static analysis.

AST-level (not jaxpr-level: see DESIGN_DECISIONS) analysis of this
codebase's JAX idioms. `analyze_paths` is the in-process API the
tier-1 gate uses; `tools/tpu_lint.py` is the CLI. The package
`__init__` forwards every name here lazily — this module is the
implementation, loaded only when analysis actually runs, never by a
plain `import paddle_tpu`.

Importing this package must not initialize a JAX backend — it reads
only `paddle_tpu.jit.introspect` (pure metadata) from the framework.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from .baseline import (BaselineError, apply_baseline, load_baseline,
                       write_baseline)
from .engine import ModuleAnalysis
from .findings import (Finding, apply_suppressions, assign_ids,
                       parse_suppressions)
from .rules import RULES, all_rule_ids

__all__ = ["analyze_file", "analyze_paths", "collect_files", "Finding",
           "Result", "RULES", "all_rule_ids", "load_baseline",
           "apply_baseline", "write_baseline", "BaselineError"]


@dataclass
class Result:
    findings: list = field(default_factory=list)
    files: list = field(default_factory=list)
    parse_errors: list = field(default_factory=list)   # (path, message)
    stale_baseline: list = field(default_factory=list)

    def new_findings(self):
        return [f for f in self.findings
                if not f.suppressed and not f.baselined]

    def per_rule_counts(self):
        out = {r: 0 for r in all_rule_ids()}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out


def _module_name(path):
    """Dotted module name when the file sits under a package root we
    know (relative imports resolve through it)."""
    parts = os.path.normpath(path).split(os.sep)
    if "paddle_tpu" in parts:
        parts = parts[parts.index("paddle_tpu"):]
        if parts[-1].endswith(".py"):
            parts[-1] = parts[-1][:-3]
        # keep the trailing '__init__': the engine derives the package
        # for relative imports by dropping the LAST component, which
        # for a package __init__ must yield the package itself
        return ".".join(parts)
    return os.path.basename(path)[:-3] if path.endswith(".py") else path


#: Paths in findings (and therefore finding IDs) are made relative to
#: the REPO ROOT, never the cwd — the committed baseline must match
#: no matter where the gate is invoked from.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _display_path(path):
    if not os.path.isabs(path) and not os.path.exists(path):
        # synthetic name for an in-memory snippet: keep verbatim
        return os.path.normpath(path).replace(os.sep, "/")
    p = os.path.abspath(path)
    try:
        rel = os.path.relpath(p, _REPO_ROOT)
    except ValueError:
        rel = p
    if not rel.startswith(".."):
        p = rel
    return p.replace(os.sep, "/")


def analyze_file(path, src=None):
    """-> list of findings for one file (IDs not yet assigned). A
    syntax error yields a single TPU000 finding — unparseable files
    are REPORTED, never silently dropped."""
    display = _display_path(path)
    if src is None:
        with open(path, encoding="utf-8", errors="replace") as f:
            src = f.read()
    try:
        mod = ModuleAnalysis(display, src, module_name=_module_name(path))
    except SyntaxError as e:
        return [Finding(rule="TPU000", path=display,
                        line=e.lineno or 1, col=(e.offset or 1) - 1,
                        message=f"unparseable file: {e.msg}")], None
    findings = []
    for rule_id in all_rule_ids():
        check = RULES[rule_id][2]
        if check is not None:
            findings.extend(check(mod))
    apply_suppressions(findings, parse_suppressions(src))
    return findings, mod


def collect_files(paths):
    """Expand files/dirs into a sorted .py file list."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for n in sorted(names):
                    if n.endswith(".py"):
                        out.append(os.path.join(root, n))
        else:
            out.append(p)
    return out


def analyze_paths(paths, baseline=None):
    """Analyze files/directories. `baseline` is {id: entry} (see
    load_baseline). Returns Result with stable IDs assigned and
    suppressions/baseline applied."""
    res = Result()
    for path in collect_files(paths):
        findings, _mod = analyze_file(path)
        res.files.append(_display_path(path))
        for f in findings:
            if f.rule == "TPU000":
                res.parse_errors.append((f.path, f.message))
        res.findings.extend(findings)
    assign_ids(res.findings)
    if baseline:
        res.stale_baseline = apply_baseline(res.findings, baseline)
    else:
        res.stale_baseline = []
    res.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return res
