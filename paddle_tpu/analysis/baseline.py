"""Committed-baseline support: grandfathered findings.

The baseline is a JSON file of entries ``{"id", "rule", "path",
"justification"}``. A finding whose stable ID appears in the baseline
does not fail the gate — but every entry MUST carry a non-empty
human-written justification (an empty one is a loader error: silent
grandfathering is how gates rot). Stale entries (no current finding
matches) are reported as notes so fixed hazards get un-baselined.
"""
from __future__ import annotations

import json


class BaselineError(ValueError):
    pass


def load_baseline(path):
    """-> {finding_id: entry}. Raises BaselineError on malformed or
    unjustified entries."""
    with open(path) as f:
        data = json.load(f)
    entries = data if isinstance(data, list) else data.get("entries", [])
    out = {}
    for n, e in enumerate(entries):
        if not isinstance(e, dict) or "id" not in e:
            raise BaselineError(f"baseline entry #{n} has no 'id'")
        if not str(e.get("justification", "")).strip():
            raise BaselineError(
                f"baseline entry {e['id']} has no justification — "
                "every grandfathered finding needs a written reason "
                "(or a fix)")
        out[e["id"]] = e
    return out


def apply_baseline(findings, baseline):
    """Mark findings present in the baseline; -> list of stale baseline
    ids (entries no current finding matches)."""
    live = set()
    for f in findings:
        if f.id in baseline:
            f.baselined = True
            live.add(f.id)
    return sorted(set(baseline) - live)


def write_baseline(path, findings):
    """Write the given (new, unsuppressed) findings as a baseline
    skeleton. Justifications are intentionally EMPTY — the loader
    rejects them until a human writes one per entry."""
    entries = [{
        "id": f.id, "rule": f.rule, "path": f.path, "line": f.line,
        "message": f.message, "justification": "",
    } for f in findings]
    with open(path, "w") as fh:
        json.dump({"version": 1, "entries": entries}, fh, indent=1)
        fh.write("\n")
    return len(entries)
