"""Finding model + stable IDs + inline suppressions for tpu-lint.

A finding's ID is deliberately LINE-NUMBER-FREE: it hashes
(rule, path, enclosing qualname, normalized source line text,
occurrence index), so a baseline entry survives unrelated edits that
shift the file, but changing the flagged line itself (i.e. touching
the hazard) invalidates the grandfathering and re-surfaces it.
"""
from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass, field

# `# tpu-lint: disable=TPU001` or `disable=TPU001,TPU005` — suppresses
# those rules on the SAME physical line. Each analyzer tier gets its
# own tag (tpu-lint / tpu-race), so suppressing one tier never mutes
# another's rule on the same line.
_SUPPRESS_TEMPLATE = r"#\s*{tag}:\s*disable=([A-Za-z0-9_,\s]+)"
_SUPPRESS_RE = re.compile(_SUPPRESS_TEMPLATE.format(tag="tpu-lint"))


@dataclass
class Finding:
    rule: str
    path: str          # posix-style, repo-relative when possible
    line: int          # 1-based
    col: int           # 0-based
    message: str
    qualname: str = "<module>"
    source: str = ""   # stripped text of the flagged line
    id: str = field(default="", compare=False)
    suppressed: bool = field(default=False, compare=False)
    baselined: bool = field(default=False, compare=False)

    def location(self):
        return f"{self.path}:{self.line}:{self.col + 1}"

    def render(self):
        return f"{self.location()}: {self.rule} {self.message} [{self.id}]"

    def to_dict(self):
        return {
            "id": self.id, "rule": self.rule, "path": self.path,
            "line": self.line, "col": self.col, "message": self.message,
            "qualname": self.qualname, "source": self.source,
        }


def assign_ids(findings):
    """Stable IDs: hash of line-free identity, disambiguated by
    occurrence order among identical tuples (two identical hazards on
    identical lines in one function get index 0 and 1)."""
    seen = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        key = (f.rule, f.path, f.qualname, f.source)
        idx = seen.get(key, 0)
        seen[key] = idx + 1
        digest = hashlib.sha1(
            "|".join([f.rule, f.path, f.qualname, f.source,
                      str(idx)]).encode()).hexdigest()[:10]
        f.id = f"{f.rule}:{digest}"
    return findings


def parse_suppressions(src, tag="tpu-lint"):
    """line (1-based) -> set of rule names suppressed on that line."""
    pattern = _SUPPRESS_RE if tag == "tpu-lint" \
        else re.compile(_SUPPRESS_TEMPLATE.format(tag=re.escape(tag)))
    out = {}
    for n, text in enumerate(src.splitlines(), start=1):
        m = pattern.search(text)
        if m:
            out[n] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def apply_suppressions(findings, suppressions):
    for f in findings:
        rules = suppressions.get(f.line)
        if rules and (f.rule in rules or "ALL" in rules):
            f.suppressed = True
    return findings
