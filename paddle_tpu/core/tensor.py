"""Eager Tensor — the user-facing dygraph tensor.

TPU-native analog of the reference's eager Tensor
(paddle/fluid/pybind/eager.cc + phi::DenseTensor,
paddle/phi/core/dense_tensor.h:38). Instead of owning an allocation, it
wraps a `jax.Array` (a PJRT buffer on TPU) or, during `jit.to_static`
tracing, a jax tracer — the same Python code therefore serves both the
eager path and the compiled path (the reference needs two stacks for
this: eager kernels + ProgramDesc/InterpreterCore).

Method/dunder surface mirrors python/paddle/tensor/* and the math-op
patch (paddle/fluid/pybind/eager_math_op_patch.cc); methods are installed
by paddle_tpu.ops at import time to avoid an import cycle.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes
from .autograd import is_grad_enabled, no_grad


class Tensor:
    __slots__ = (
        "_array",
        "stop_gradient",
        "_grad",
        "_creator",
        "_out_idx",
        "name",
        "persistable",
        "dist_spec",  # PartitionSpec annotation consumed by spmd.TrainStep
        "_version",  # bumped on in-place mutation; tape nodes snapshot it
        "_leaf_hooks",  # grad hooks on leaf tensors (GradNodeAccumulation)
        "__weakref__",
    )

    # make numpy defer to our __r*__ dunders
    __array_priority__ = 100

    def __init__(self, data=None, dtype=None, stop_gradient: bool = True, name: str = ""):
        if data is None:
            data = []
        if isinstance(data, Tensor):
            arr = data._array
            if dtype is not None:
                arr = arr.astype(dtypes.to_jax(dtype))
        elif isinstance(data, (jax.Array, jnp.ndarray)) and not isinstance(data, np.ndarray):
            arr = data if dtype is None else data.astype(dtypes.to_jax(dtype))
        else:
            if dtype is None:
                dtype = dtypes.infer_dtype(data)
            jd = dtypes.to_jax(dtype)
            npd = np.asarray(data)
            if jnp.issubdtype(jd, jnp.complexfloating):
                from paddle_tpu.core.device import supports_complex

                cpu = None
                if not supports_complex():
                    try:
                        cpu = jax.devices("cpu")[0]
                    except Exception:
                        cpu = None
                if cpu is not None:
                    # complex buffers live CPU-side on backends that
                    # cannot hold them (see device.supports_complex)
                    arr = jax.device_put(npd.astype(jd), cpu)
                else:
                    arr = jnp.asarray(npd, dtype=jd)
            else:
                arr = jnp.asarray(npd, dtype=jd)
        self._array = arr
        self.stop_gradient = stop_gradient
        self._grad: Optional[Tensor] = None
        self._creator = None
        self._out_idx = 0
        self.name = name
        self.persistable = False
        self.dist_spec = None
        self._version = 0
        self._leaf_hooks = None

    # -- construction ------------------------------------------------------
    @classmethod
    def _wrap(cls, array, stop_gradient: bool = True, creator=None, out_idx: int = 0):
        t = cls.__new__(cls)
        t._array = array
        t.stop_gradient = stop_gradient
        t._grad = None
        t._creator = creator
        t._out_idx = out_idx
        t.name = ""
        t.persistable = False
        t.dist_spec = None
        t._version = 0
        t._leaf_hooks = None
        return t

    # -- metadata ----------------------------------------------------------
    @property
    def shape(self):
        return list(self._array.shape)

    @property
    def ndim(self):
        return self._array.ndim

    @property
    def size(self):
        return int(np.prod(self._array.shape)) if self._array.shape else 1

    @property
    def dtype(self):
        return dtypes.canonical_name(self._array.dtype)

    @property
    def place(self):
        devs = getattr(self._array, "devices", None)
        if devs is None:
            return "traced"
        try:
            return str(next(iter(self._array.devices())))
        except Exception:
            return "traced"

    @property
    def is_leaf(self):
        return self._creator is None

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._array.shape[0]

    # -- grad --------------------------------------------------------------
    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is not None and not isinstance(value, Tensor):
            value = Tensor(value)
        self._grad = value

    def _accumulate_grad(self, ct):
        if self._grad is None:
            self._grad = Tensor._wrap(ct, stop_gradient=True)
        else:
            self._grad = Tensor._wrap(self._grad._array + ct, stop_gradient=True)

    def backward(self, grad_tensor=None, retain_graph: bool = False):
        from .autograd import run_backward

        run_backward(self, grad_tensor, retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    def clear_gradient(self):  # reference spelling
        self._grad = None

    def detach(self) -> "Tensor":
        return Tensor._wrap(self._array, stop_gradient=True)

    def detach_(self) -> "Tensor":
        self._creator = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from paddle_tpu import ops

        return ops.manipulation.clone(self)

    def register_hook(self, hook):
        """Grad hook fired when this tensor's cotangent is materialized
        during backward; analog of egr RegisterGradientHookForTensor. For
        leaf tensors the hook fires at grad accumulation time — the
        GradNodeAccumulation hook point (accumulation_node.h) that e.g.
        DataParallel reducers attach to. The hook receives/returns a
        Tensor (or None to keep unchanged). Returns a handle with
        .remove()."""

        def array_hook(ct, _hook=hook):
            out = _hook(Tensor._wrap(ct))
            if out is None:
                return None
            return out._array if isinstance(out, Tensor) else out

        if self._creator is None:
            if self._leaf_hooks is None:
                self._leaf_hooks = []
            hooks_list = self._leaf_hooks
            hooks_list.append(array_hook)
        else:
            node, idx = self._creator, self._out_idx
            hooks_list = node.out_hooks.setdefault(idx, [])
            hooks_list.append(array_hook)

        class _Handle:
            def remove(self, _lst=hooks_list, _h=array_hook):
                if _h in _lst:
                    _lst.remove(_h)

        return _Handle()

    # -- host interop ------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._array)

    def item(self):
        return self._array.item()

    def tolist(self):
        return np.asarray(self._array).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._array)
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self._array)

    def __int__(self):
        return int(self._array)

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "truth value of a multi-element Tensor is ambiguous; use .any()/.all()"
            )
        return bool(self._array)

    def __repr__(self):
        sg = self.stop_gradient
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype}, stop_gradient={sg},\n"
            f"       {np.asarray(jax.device_get(self._array)) if not self._is_traced() else '<traced>'})"
        )

    def _is_traced(self) -> bool:
        return not isinstance(self._array, jax.Array) or isinstance(
            self._array, jax.core.Tracer
        )

    # -- in-place mutation (eager only) ------------------------------------
    def _mutate(self, new_array):
        """THE in-place mutation point: every op that overwrites the
        stored value routes here so the version counter (checked at
        backward against tape snapshots) can never be skipped."""
        self._array = new_array
        self._version += 1

    def set_value(self, value):
        if isinstance(value, Tensor):
            arr = value._array
        else:
            arr = jnp.asarray(np.asarray(value))
        self._mutate(arr.astype(self._array.dtype).reshape(self._array.shape))

    def copy_(self, other, blocking=True):
        self.set_value(other)
        return self

    def _in_place_update(self, new_array):
        """Optimizer-style parameter update; keeps identity and autograd
        leaf status. Old buffer is donated conceptually (PJRT frees it)."""
        self._mutate(new_array)

    # -- iteration / indexing installed by ops package ---------------------
    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)


def _flatten_tensors(x):
    """Utility: pytree leaves -> arrays for functional APIs."""
    return jax.tree_util.tree_map(
        lambda v: v._array if isinstance(v, Tensor) else v, x
    )


class Parameter(Tensor):
    """Trainable tensor; analog of paddle's Parameter/EagerParamBase
    (python/paddle/fluid/framework.py Parameter). stop_gradient defaults
    False and it is persistable (enters state_dict)."""

    # _asp_mask: structured-sparsity mask (incubate.asp), carried by the
    # param itself so masks stay scoped to their model
    __slots__ = ("trainable", "optimize_attr", "regularizer", "_asp_mask")

    def __init__(self, data, dtype=None, name: str = "", trainable: bool = True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
