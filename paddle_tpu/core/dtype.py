"""Dtype registry and promotion helpers.

TPU-native analog of the reference's phi dtype system
(paddle/phi/common/data_type.h). We standardise on strings that map onto
jax.numpy dtypes; bfloat16 is first-class (it is the TPU MXU's native
matmul dtype), fp16 exists only for API parity.

Deliberate TPU-first deviation from the reference: 64-bit numeric types
are ALIASES for their 32-bit counterparts ("int64"->int32,
"float64"->float32). TPUs have no native f64 and emulate s64; XLA's
index type is s32. The API accepts the 64-bit names everywhere (paddle
parity — e.g. int64 labels) but storage and compute are 32-bit.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# canonical name -> jnp dtype
_DTYPE_MAP = {
    "float32": jnp.float32,
    "float64": jnp.float64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,  # unreachable: aliased to int32
    "uint8": jnp.uint8,
    "uint16": jnp.uint16,
    "uint32": jnp.uint32,
    "bool": jnp.bool_,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

_ALIASES = {
    "float": "float32",
    "double": "float32",
    "half": "float16",
    "bf16": "bfloat16",
    "fp16": "float16",
    "fp32": "float32",
    "int": "int32",
    "long": "int32",
    # 64-bit -> 32-bit (TPU-native; see module docstring)
    "int64": "int32",
    "uint64": "uint32",
    "float64": "float32",
    "complex128": "complex64",
}

_default_dtype = "float32"


def set_default_dtype(d) -> None:
    global _default_dtype
    name = canonical_name(d)
    if name not in ("float32", "float64", "float16", "bfloat16"):
        raise ValueError(f"default dtype must be floating, got {name}")
    _default_dtype = name


def get_default_dtype() -> str:
    return _default_dtype


def canonical_name(d) -> str:
    """Normalise any dtype-ish object to a canonical string name."""
    if d is None:
        return _default_dtype
    if isinstance(d, str):
        d = _ALIASES.get(d, d)
        if d in _DTYPE_MAP:
            return d
        # fall through to numpy parsing for things like 'f4'
    try:
        name = jnp.dtype(d).name
    except TypeError as e:  # pragma: no cover
        raise TypeError(f"unsupported dtype: {d!r}") from e
    name = _ALIASES.get(name, name)
    if name not in _DTYPE_MAP:
        raise TypeError(f"unsupported dtype: {d!r}")
    return name


def to_jax(d):
    """Any dtype-ish -> jnp dtype object."""
    return jnp.dtype(_DTYPE_MAP[canonical_name(d)])


def is_floating(d) -> bool:
    return jnp.issubdtype(to_jax(d), jnp.floating)


def is_integer(d) -> bool:
    return jnp.issubdtype(to_jax(d), jnp.integer)


def is_inexact(d) -> bool:
    return jnp.issubdtype(to_jax(d), jnp.inexact)


def infer_dtype(value):
    """Dtype for a host value the way the reference's to_tensor does:
    python float -> default float dtype, python int -> int64, bool -> bool.
    """
    if isinstance(value, bool):
        return "bool"
    if isinstance(value, int):
        return "int32"
    if isinstance(value, float):
        return _default_dtype
    if isinstance(value, complex):
        return "complex64"
    arr = np.asarray(value)
    if arr.dtype == np.float64:
        # match paddle.to_tensor: host doubles become default float dtype
        return _default_dtype
    return canonical_name(arr.dtype)
