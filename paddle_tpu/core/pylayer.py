"""PyLayer — user-defined autograd ops on the tape engine.

Analog of the reference's eager PyLayer
(paddle/fluid/eager/pylayer/py_layer_node.h, python API
python/paddle/autograd/py_layer.py): `forward` runs with grad recording
disabled, a single tape Node is recorded whose backward calls the
user-defined `backward` with the output cotangents.

    class cus_tanh(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = paddle.tanh(x)
            ctx.save_for_backward(y)
            return y

        @staticmethod
        def backward(ctx, dy):
            y, = ctx.saved_tensor()
            return dy * (1 - paddle.square(y))

    out = cus_tanh.apply(x)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .autograd import Node, is_grad_enabled, no_grad
from .tensor import Tensor


class PyLayerContext:
    """The `ctx` handed to forward/backward (analog of PyLayerContext in
    python/paddle/autograd/py_layer.py)."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved

    # reference-compat aliases
    saved_tensors = property(lambda self: self._saved)

    def mark_not_inplace(self, *tensors):
        self.not_inplace_tensors = tuple(tensors)

    def set_materialize_grads(self, value: bool):
        self.materialize_grads = bool(value)


def _flatten_tensors(args):
    out = []
    for a in args:
        if isinstance(a, Tensor):
            out.append(a)
        elif isinstance(a, (list, tuple)):
            out.extend(_flatten_tensors(a))
    return out


class PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError("PyLayer subclasses must define forward")

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError("PyLayer subclasses must define backward")

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = _flatten_tensors(args) + _flatten_tensors(
            list(kwargs.values()))
        needs_grad = is_grad_enabled() and any(
            not t.stop_gradient and jnp.issubdtype(t._array.dtype, jnp.inexact)
            for t in tensor_inputs)

        if needs_grad and any(
                isinstance(t._array, jax.core.Tracer) for t in tensor_inputs):
            # Inside an outer jax trace (TrainStep, to_static, vmap): the
            # outer AD would differentiate the forward ops directly and
            # silently skip the user backward. Route through jax.custom_vjp
            # so the custom gradient survives tracing.
            return cls._apply_traced(args, kwargs, tensor_inputs)

        # ops inside forward are NOT recorded — the PyLayer node replaces
        # them (py_layer_node.h semantics)
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)

        if not needs_grad:
            return outs

        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)
        out_tensors = [o for o in out_list if isinstance(o, Tensor)]
        out_specs = [(o._array.shape, o._array.dtype) for o in out_tensors]
        diff_inputs = [t for t in tensor_inputs
                       if not t.stop_gradient
                       and jnp.issubdtype(t._array.dtype, jnp.inexact)]

        def vjp_fn(cts):
            ct_list = list(cts) if isinstance(cts, (tuple, list)) else [cts]
            ct_tensors = [Tensor._wrap(c) for c in ct_list]
            with no_grad():
                gin = cls.backward(ctx, *ct_tensors)
            gin = list(gin) if isinstance(gin, (tuple, list)) else [gin]
            # paddle contract: backward returns one grad per *forward
            # tensor input that requires grad*, in order (None allowed)
            if len(gin) == len(tensor_inputs) and len(tensor_inputs) != len(diff_inputs):
                gin = [g for t, g in zip(tensor_inputs, gin)
                       if not t.stop_gradient
                       and jnp.issubdtype(t._array.dtype, jnp.inexact)]
            if len(gin) != len(diff_inputs):
                raise RuntimeError(
                    f"{cls.__name__}.backward returned {len(gin)} grads for "
                    f"{len(diff_inputs)} differentiable inputs")
            return tuple(
                None if g is None else (g._array if isinstance(g, Tensor) else jnp.asarray(g))
                for g in gin)

        node = Node(cls.__name__, vjp_fn, diff_inputs, out_specs)
        idx = 0
        rewrapped = []
        for o in out_list:
            if isinstance(o, Tensor):
                rewrapped.append(Tensor._wrap(o._array, stop_gradient=False,
                                              creator=node, out_idx=idx))
                idx += 1
            else:
                rewrapped.append(o)
        return rewrapped[0] if single else tuple(rewrapped)

    @classmethod
    def _normalize_grads(cls, gin, tensor_inputs, diff_mask):
        """Map the user backward's return to one cotangent per tensor
        input (paddle contract: one grad per differentiable input in
        order, or one per tensor input with None holes)."""
        gin = list(gin) if isinstance(gin, (tuple, list)) else [gin]
        n_diff = sum(diff_mask)
        if len(gin) == len(tensor_inputs):
            pass  # already aligned with all tensor inputs
        elif len(gin) == n_diff:
            full, it = [], iter(gin)
            for m in diff_mask:
                full.append(next(it) if m else None)
            gin = full
        else:
            raise RuntimeError(
                f"{cls.__name__}.backward returned {len(gin)} grads for "
                f"{n_diff} differentiable inputs")
        return gin

    @classmethod
    def _apply_traced(cls, args, kwargs, tensor_inputs):
        """custom_vjp path used when inputs hold jax tracers.

        All tensor inputs become primal arguments (closing over tracers in
        a custom_vjp primal is disallowed by jax); saved-for-backward
        tensors travel as custom_vjp residuals; non-array ctx state rides
        a Python cell captured at trace time.
        """
        diff_mask = [not t.stop_gradient
                     and jnp.issubdtype(t._array.dtype, jnp.inexact)
                     for t in tensor_inputs]
        in_arrays = tuple(t._array for t in tensor_inputs)
        index_of = {id(t): i for i, t in enumerate(tensor_inputs)}
        # (ctx, single, is_tensor_mask, non_tensor_outputs) from the most
        # recent forward trace
        cell = []

        def _rebuild(obj, arrays):
            if isinstance(obj, Tensor):
                i = index_of.get(id(obj))
                if i is None:
                    return obj
                nt = Tensor._wrap(arrays[i],
                                  stop_gradient=obj.stop_gradient)
                return nt
            if isinstance(obj, tuple):
                return tuple(_rebuild(o, arrays) for o in obj)
            if isinstance(obj, list):
                return [_rebuild(o, arrays) for o in obj]
            return obj

        def _fwd_impl(arrays):
            fctx = PyLayerContext()
            new_args = tuple(_rebuild(a, arrays) for a in args)
            new_kwargs = {k: _rebuild(v, arrays) for k, v in kwargs.items()}
            with no_grad():
                outs = cls.forward(fctx, *new_args, **new_kwargs)
            single = not isinstance(outs, (tuple, list))
            out_list = [outs] if single else list(outs)
            mask = [isinstance(o, Tensor) for o in out_list]
            non_tensor = [o for o in out_list if not isinstance(o, Tensor)]
            cell.clear()
            cell.append((fctx, single, mask, non_tensor))
            out_arrays = tuple(o._array for o in out_list
                               if isinstance(o, Tensor))
            saved = tuple(s._array if isinstance(s, Tensor) else jnp.asarray(s)
                          for s in fctx._saved)
            return out_arrays, saved

        def _prim(*arrays):
            return _fwd_impl(arrays)[0]

        def _prim_fwd(*arrays):
            return _fwd_impl(arrays)

        def _prim_bwd(saved, cts):
            fctx = cell[0][0] if cell else PyLayerContext()
            fctx._saved = tuple(Tensor._wrap(s) for s in saved)
            ct_tensors = [Tensor._wrap(c) for c in cts]
            with no_grad():
                gin = cls.backward(fctx, *ct_tensors)
            gin = cls._normalize_grads(gin, tensor_inputs, diff_mask)
            import numpy as _np
            out = []
            for g, a in zip(gin, in_arrays):
                if not jnp.issubdtype(a.dtype, jnp.inexact):
                    # jax's cotangent type for integer/bool primals
                    out.append(_np.zeros(a.shape, jax.dtypes.float0))
                elif g is None:
                    out.append(jnp.zeros(a.shape, a.dtype))
                else:
                    out.append(g._array if isinstance(g, Tensor)
                               else jnp.asarray(g))
            return tuple(out)

        f = jax.custom_vjp(_prim)
        f.defvjp(_prim_fwd, _prim_bwd)
        out_arrays = f(*in_arrays)
        _, single, mask, non_tensor = cell[0]

        out_specs = [(a.shape, a.dtype) for a in out_arrays]
        diff_inputs = [t for t, m in zip(tensor_inputs, diff_mask) if m]

        def lazy_vjp(cts, _f=f, _in=in_arrays):
            ct_list = tuple(cts) if isinstance(cts, (tuple, list)) else (cts,)
            _, vjp_fn = jax.vjp(_f, *_in)
            full = vjp_fn(ct_list)
            return tuple(g for g, m in zip(full, diff_mask) if m)

        node = Node(cls.__name__, lazy_vjp, diff_inputs, out_specs)
        arr_it = iter(out_arrays)
        nt_it = iter(non_tensor)
        rewrapped, idx = [], 0
        for m in mask:
            if m:
                rewrapped.append(Tensor._wrap(next(arr_it),
                                              stop_gradient=False,
                                              creator=node, out_idx=idx))
                idx += 1
            else:
                rewrapped.append(next(nt_it))
        return rewrapped[0] if single else tuple(rewrapped)


class PyLayerContextLegacy(PyLayerContext):
    pass
