"""PyLayer — user-defined autograd ops on the tape engine.

Analog of the reference's eager PyLayer
(paddle/fluid/eager/pylayer/py_layer_node.h, python API
python/paddle/autograd/py_layer.py): `forward` runs with grad recording
disabled, a single tape Node is recorded whose backward calls the
user-defined `backward` with the output cotangents.

    class cus_tanh(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = paddle.tanh(x)
            ctx.save_for_backward(y)
            return y

        @staticmethod
        def backward(ctx, dy):
            y, = ctx.saved_tensor()
            return dy * (1 - paddle.square(y))

    out = cus_tanh.apply(x)
"""
from __future__ import annotations

import jax.numpy as jnp

from .autograd import Node, is_grad_enabled, no_grad
from .tensor import Tensor


class PyLayerContext:
    """The `ctx` handed to forward/backward (analog of PyLayerContext in
    python/paddle/autograd/py_layer.py)."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved

    # reference-compat aliases
    saved_tensors = property(lambda self: self._saved)

    def mark_not_inplace(self, *tensors):
        self.not_inplace_tensors = tuple(tensors)

    def set_materialize_grads(self, value: bool):
        self.materialize_grads = bool(value)


def _flatten_tensors(args):
    out = []
    for a in args:
        if isinstance(a, Tensor):
            out.append(a)
        elif isinstance(a, (list, tuple)):
            out.extend(_flatten_tensors(a))
    return out


class PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError("PyLayer subclasses must define forward")

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError("PyLayer subclasses must define backward")

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = _flatten_tensors(args) + _flatten_tensors(
            list(kwargs.values()))
        needs_grad = is_grad_enabled() and any(
            not t.stop_gradient and jnp.issubdtype(t._array.dtype, jnp.inexact)
            for t in tensor_inputs)

        # ops inside forward are NOT recorded — the PyLayer node replaces
        # them (py_layer_node.h semantics)
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)

        if not needs_grad:
            return outs

        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)
        out_tensors = [o for o in out_list if isinstance(o, Tensor)]
        out_specs = [(o._array.shape, o._array.dtype) for o in out_tensors]
        diff_inputs = [t for t in tensor_inputs
                       if not t.stop_gradient
                       and jnp.issubdtype(t._array.dtype, jnp.inexact)]

        def vjp_fn(cts):
            ct_list = list(cts) if isinstance(cts, (tuple, list)) else [cts]
            ct_tensors = [Tensor._wrap(c) for c in ct_list]
            with no_grad():
                gin = cls.backward(ctx, *ct_tensors)
            gin = list(gin) if isinstance(gin, (tuple, list)) else [gin]
            # paddle contract: backward returns one grad per *forward
            # tensor input that requires grad*, in order (None allowed)
            if len(gin) == len(tensor_inputs) and len(tensor_inputs) != len(diff_inputs):
                gin = [g for t, g in zip(tensor_inputs, gin)
                       if not t.stop_gradient
                       and jnp.issubdtype(t._array.dtype, jnp.inexact)]
            if len(gin) != len(diff_inputs):
                raise RuntimeError(
                    f"{cls.__name__}.backward returned {len(gin)} grads for "
                    f"{len(diff_inputs)} differentiable inputs")
            return tuple(
                None if g is None else (g._array if isinstance(g, Tensor) else jnp.asarray(g))
                for g in gin)

        node = Node(cls.__name__, vjp_fn, diff_inputs, out_specs)
        idx = 0
        rewrapped = []
        for o in out_list:
            if isinstance(o, Tensor):
                rewrapped.append(Tensor._wrap(o._array, stop_gradient=False,
                                              creator=node, out_idx=idx))
                idx += 1
            else:
                rewrapped.append(o)
        return rewrapped[0] if single else tuple(rewrapped)


class PyLayerContextLegacy(PyLayerContext):
    pass
