"""RNG state — analog of phi::Generator (paddle/phi/core/generator.h:23).

The reference keeps per-device stateful Philox generators. The TPU-native
design is a functional JAX PRNG key chain: a global Generator holds one
key and splits a fresh subkey per random op. Parallel determinism across
mesh axes is handled by RNGStatesTracker (distributed/random.py), the
analog of fleet/layers/mpu/random.py:35.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np


class Generator:
    """Stateful wrapper over a jax PRNG key chain."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed)
            self._key = jax.random.key(int(seed))
            self._count = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        """Split and return a fresh subkey (thread-safe)."""
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            self._count += 1
            return sub

    def get_state(self):
        with self._lock:
            return (self._seed, self._count, jax.random.key_data(self._key))

    def set_state(self, state):
        seed, count, key_data = state
        with self._lock:
            self._seed = seed
            self._count = count
            self._key = jax.random.wrap_key_data(np.asarray(key_data))


# created lazily: constructing a PRNG key initializes the XLA backend,
# and `import paddle_tpu` must stay legal BEFORE jax.distributed.initialize
# (multi-process bootstrap, parallel.py init_parallel_env)
_default_generator = None


def _gen() -> Generator:
    global _default_generator
    if _default_generator is None:
        _default_generator = Generator(0)
    return _default_generator

# Traced-key scope: inside a compiled step (TrainStep/DistributedTrainStep)
# the per-step PRNG key is a *traced argument*; random ops must derive from
# it instead of the eager generator, otherwise the key is baked into the
# trace as a constant and every compiled step reuses the identical dropout
# mask (ADVICE r1 medium). Each next_key() inside the scope folds in a
# fresh counter — the fold sequence is fixed at trace time, so each random
# op site gets a distinct, step-varying key.
_key_scope_tls = threading.local()


@contextlib.contextmanager
def key_scope(key):
    prev = getattr(_key_scope_tls, "scope", None)
    _key_scope_tls.scope = [key, 0]
    try:
        yield
    finally:
        _key_scope_tls.scope = prev


def in_key_scope() -> bool:
    return getattr(_key_scope_tls, "scope", None) is not None


def default_generator() -> Generator:
    return _gen()


def seed(value: int) -> Generator:
    """Analog of paddle.seed: reseeds the global generator."""
    return _gen().manual_seed(value)


def next_key():
    scope = getattr(_key_scope_tls, "scope", None)
    if scope is not None:
        k = jax.random.fold_in(scope[0], scope[1])
        scope[1] += 1
    else:
        k = _gen().next_key()
    # active key folds (e.g. per-slot/per-tick indices inside lax.scan
    # bodies — traced once, so without the fold every iteration would
    # reuse one identical key per call site)
    for f in getattr(_key_scope_tls, "folds", ()):
        k = jax.random.fold_in(k, f)
    return k


@contextlib.contextmanager
def fold_key(idx):
    """Fold `idx` (may be a traced int, e.g. a lax.scan counter) into
    every key drawn inside the context. Nestable; folds compose."""
    prev = tuple(getattr(_key_scope_tls, "folds", ()))
    _key_scope_tls.folds = prev + (idx,)
    try:
        yield
    finally:
        _key_scope_tls.folds = prev


def get_rng_state():
    return _gen().get_state()


def set_rng_state(state):
    _gen().set_state(state)
