"""RNG state — analog of phi::Generator (paddle/phi/core/generator.h:23).

The reference keeps per-device stateful Philox generators. The TPU-native
design is a functional JAX PRNG key chain: a global Generator holds one
key and splits a fresh subkey per random op. Parallel determinism across
mesh axes is handled by RNGStatesTracker (distributed/random.py), the
analog of fleet/layers/mpu/random.py:35.
"""
from __future__ import annotations

import threading

import jax
import numpy as np


class Generator:
    """Stateful wrapper over a jax PRNG key chain."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed)
            self._key = jax.random.key(int(seed))
            self._count = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        """Split and return a fresh subkey (thread-safe)."""
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            self._count += 1
            return sub

    def get_state(self):
        with self._lock:
            return (self._seed, self._count, jax.random.key_data(self._key))

    def set_state(self, state):
        seed, count, key_data = state
        with self._lock:
            self._seed = seed
            self._count = count
            self._key = jax.random.wrap_key_data(np.asarray(key_data))


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(value: int) -> Generator:
    """Analog of paddle.seed: reseeds the global generator."""
    return _default_generator.manual_seed(value)


def next_key():
    return _default_generator.next_key()


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)
