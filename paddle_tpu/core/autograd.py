"""Define-by-run autograd engine.

TPU-native analog of the reference's eager autograd
(paddle/fluid/eager/backward.cc:105 RunBackward,
paddle/fluid/eager/grad_node_info.h:168 GradNodeBase): every differentiable
op records a `Node` holding a jax VJP closure; `backward()` walks nodes in
reverse creation order (a tape — creation order IS a topological order for
define-by-run graphs) and accumulates cotangents. Leaf accumulation is the
analog of GradNodeAccumulation (eager/accumulation/accumulation_node.h).

Because the VJP closures hold jax arrays (residuals) and call jax ops, the
whole engine works identically on concrete device arrays (eager mode) and
on tracers (inside `paddle_tpu.jit.to_static` — where the entire
forward+backward collapses into one XLA computation).
"""
from __future__ import annotations

import contextlib
from typing import Callable, List, Optional, Sequence

import jax.numpy as jnp

_grad_enabled = True
_node_counter = 0


def is_grad_enabled() -> bool:
    return _grad_enabled


def set_grad_enabled(mode: bool):
    global _grad_enabled
    _grad_enabled = bool(mode)


@contextlib.contextmanager
def no_grad():
    """Analog of paddle.no_grad (dygraph tracer has_grad=False)."""
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = False
    try:
        yield
    finally:
        _grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    global _grad_enabled
    prev = _grad_enabled
    _grad_enabled = True
    try:
        yield
    finally:
        _grad_enabled = prev


class Node:
    """One recorded op on the tape; analog of a generated GradNode.

    Attributes:
      vjp_fn: closure from jax.vjp — maps output cotangents to input
        cotangents. Holds forward residuals (the TensorWrapper analog,
        eager/tensor_wrapper.h).
      inputs: the input Tensors (only those participating in autodiff).
      out_specs: (shape, dtype) per output, for synthesizing zero
        cotangents for outputs never used downstream.
    """

    __slots__ = (
        "name",
        "seq",
        "vjp_fn",
        "inputs",
        "input_versions",
        "out_specs",
        "out_cts",
        "hooks",
        "out_hooks",
    )

    def __init__(self, name: str, vjp_fn: Callable, inputs: Sequence, out_specs: List):
        global _node_counter
        _node_counter += 1
        self.name = name
        self.seq = _node_counter
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)
        # version snapshot: detects in-place mutation (setitem/set_value/
        # optimizer update) between forward record and backward — the
        # analog of torch/paddle's saved-tensor version counter. Inputs
        # with stop_gradient=True are not tracked: mutating them cannot
        # change any gradient this engine computes (vjp closures capture
        # the pre-mutation arrays), and torch/paddle do not track
        # non-requires-grad tensors either.
        self.input_versions = [
            None if getattr(t, "stop_gradient", True)
            else getattr(t, "_version", 0)
            for t in inputs]
        self.out_specs = out_specs
        self.out_cts: List[Optional[object]] = [None] * len(out_specs)
        self.hooks: List[Callable] = []
        self.out_hooks: dict = {}

    def accumulate_out_ct(self, idx: int, ct):
        cur = self.out_cts[idx]
        self.out_cts[idx] = ct if cur is None else cur + ct

    def materialized_cts(self):
        cts = []
        for i, (ct, (shape, dtype)) in enumerate(zip(self.out_cts, self.out_specs)):
            if ct is None:
                ct = jnp.zeros(shape, dtype)
            for hook in self.out_hooks.get(i, ()):
                out = hook(ct)
                if out is not None:
                    ct = out
            cts.append(ct)
        return tuple(cts) if len(cts) != 1 else cts[0]

    def __repr__(self):
        return f"<Node {self.name} seq={self.seq}>"


def _collect_graph(root_node: Node):
    """DFS from the root collecting reachable nodes."""
    seen = {}
    stack = [root_node]
    while stack:
        n = stack.pop()
        if n.seq in seen:
            continue
        seen[n.seq] = n
        for t in n.inputs:
            creator = t._creator
            if creator is not None and creator.seq not in seen:
                stack.append(creator)
    return seen


def run_backward(tensors, grad_tensors=None, retain_graph: bool = False,
                 sink: dict = None):
    """Analog of egr::RunBackward (paddle/fluid/eager/backward.cc:105).

    Seeds cotangents on `tensors`, processes reachable nodes in reverse
    creation order, accumulates `.grad` on leaf tensors with
    stop_gradient=False. If `sink` is given (paddle.grad path), leaf
    cotangents accumulate into sink[id(tensor)] instead of `.grad` — no
    tensor state is mutated.
    """
    from .tensor import Tensor  # local import to avoid cycle

    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # leaf cotangents accumulate here first so hooks fire ONCE per leaf
    # with the fully-summed gradient (GradNodeAccumulation semantics) —
    # not once per partial contribution
    leaf_cts: dict = {}

    def leaf_accumulate(t, ct):
        entry = leaf_cts.get(id(t))
        if entry is None:
            leaf_cts[id(t)] = [t, ct]
        else:
            entry[1] = entry[1] + ct

    def flush_leaves():
        for t, ct in leaf_cts.values():
            if getattr(t, "_leaf_hooks", None):
                for hook in list(t._leaf_hooks):
                    out = hook(ct)
                    if out is not None:
                        ct = out
            if sink is not None:
                key = id(t)
                sink[key] = ct if key not in sink else sink[key] + ct
            else:
                t._accumulate_grad(ct)

    roots = []
    with no_grad():
        for t, g in zip(tensors, grad_tensors):
            if g is None:
                seed_ct = jnp.ones(t._array.shape, t._array.dtype)
            else:
                seed_ct = g._array if isinstance(g, Tensor) else jnp.asarray(g)
            if t._creator is not None:
                t._creator.accumulate_out_ct(t._out_idx, seed_ct)
                roots.append(t._creator)
            elif not t.stop_gradient:
                leaf_accumulate(t, seed_ct)

        if not roots:
            flush_leaves()
            return

        nodes = {}
        for r in roots:
            nodes.update(_collect_graph(r))

        for seq in sorted(nodes.keys(), reverse=True):
            node = nodes[seq]
            if all(ct is None for ct in node.out_cts):
                continue  # branch never contributed to the loss
            for t, ver in zip(node.inputs, node.input_versions):
                if ver is not None and getattr(t, "_version", 0) != ver:
                    raise RuntimeError(
                        f"a tensor used by '{node.name}' was mutated in "
                        f"place (version {ver} -> {t._version}) after the "
                        f"forward pass; backward would silently use the "
                        f"pre-mutation value (torch/paddle version-counter "
                        f"semantics forbid this)")
            cts = node.materialized_cts()
            in_cts = node.vjp_fn(cts)
            for hook in node.hooks:
                in_cts = hook(in_cts) or in_cts
            for t, ct in zip(node.inputs, in_cts):
                if ct is None:
                    continue
                # jax uses float0 for nondifferentiable (integer) inputs
                if getattr(ct, "dtype", None) is not None and ct.dtype.name == "float0":
                    continue
                if t._creator is not None:
                    t._creator.accumulate_out_ct(t._out_idx, ct)
                elif not t.stop_gradient:
                    leaf_accumulate(t, ct)
            if not retain_graph:
                node.vjp_fn = None
                node.out_cts = [None] * len(node.out_specs)
            else:
                node.out_cts = [None] * len(node.out_specs)

        flush_leaves()


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         allow_unused=False):
    """Analog of paddle.grad (GeneralGrad, eager/general_grad.h): returns
    grads of `outputs` w.r.t. `inputs` without touching `.grad` slots.

    Implemented by temporarily re-pointing leaf accumulation into a side
    table. create_graph (double grad) is supported because the engine runs
    on tracers just as well as on concrete arrays — callers wanting higher
    order grads should use the functional `paddle_tpu.jit` APIs instead.
    """
    from .tensor import Tensor

    if not isinstance(outputs, (list, tuple)):
        outputs = [outputs]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]

    saved = [t.stop_gradient for t in inputs]
    for t in inputs:
        t.stop_gradient = False
    sink: dict = {}
    try:
        run_backward(outputs, grad_outputs, retain_graph=bool(retain_graph),
                     sink=sink)
        results = []
        for t in inputs:
            ct = sink.get(id(t))
            if ct is None and not allow_unused:
                ct = jnp.zeros(t._array.shape, t._array.dtype)
            results.append(Tensor._wrap(ct) if ct is not None else None)
        return results
    finally:
        for t, sg in zip(inputs, saved):
            t.stop_gradient = sg
