from . import autograd, device, dtype, random
from .autograd import enable_grad, grad, is_grad_enabled, no_grad, set_grad_enabled
from .device import get_device, set_device
from .dtype import get_default_dtype, set_default_dtype
from .random import get_rng_state, seed, set_rng_state
from .tensor import Parameter, Tensor

__all__ = [
    "Tensor",
    "Parameter",
    "no_grad",
    "enable_grad",
    "grad",
    "set_device",
    "get_device",
    "seed",
    "set_default_dtype",
    "get_default_dtype",
]
