"""Device API — analog of python/paddle/device/__init__.py:355 (set_device).

On TPU there is exactly one native accelerator; "places" map onto jax
devices. `set_device('tpu')`/`set_device('cpu')` select the default jax
device used for newly created tensors. Unlike the reference's
DeviceContextPool (paddle/fluid/platform/device_context.h:353), there is
no per-stream context to manage: XLA/PJRT owns streams and ordering.
"""
from __future__ import annotations

import jax

_current_place = None


class Place:
    """A device place, e.g. Place('tpu', 0). Analog of phi::Place."""

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def jax_device(self):
        devs = [d for d in jax.devices() if _platform_of(d) == self.device_type]
        if not devs:
            devs = jax.devices("cpu")
        return devs[min(self.device_id, len(devs) - 1)]


def _platform_of(d) -> str:
    p = d.platform
    return "tpu" if p in ("tpu", "axon") else p


def _parse(device: str) -> Place:
    device = device.lower()
    if ":" in device:
        kind, idx = device.split(":", 1)
        return Place(kind, int(idx))
    return Place(device, 0)


def set_device(device: str) -> Place:
    """Select the default device; analog of paddle.device.set_device
    (python/paddle/device/__init__.py:355)."""
    global _current_place
    place = _parse(device)
    # validate it exists; fall back to whatever jax default is
    place.jax_device()
    _current_place = place
    return place


def get_device() -> str:
    p = get_place()
    return f"{p.device_type}:{p.device_id}"


def get_place() -> Place:
    global _current_place
    if _current_place is None:
        d = jax.devices()[0]
        _current_place = Place(_platform_of(d), 0)
    return _current_place


def default_jax_device():
    return get_place().jax_device()


_supports_complex = None


def supports_complex() -> bool:
    """Whether the default backend can hold complex buffers. Production
    CPU/GPU/TPU XLA can; the experimental axon tunnel (remote-compile
    dev TPU) cannot — and a failed op permanently wedges its process, so
    detection is by platform config (side-effect-free), not probing."""
    global _supports_complex
    if _supports_complex is None:
        import os

        platforms = str(getattr(jax.config, "jax_platforms", None) or
                        os.environ.get("JAX_PLATFORMS", "") or "")
        _supports_complex = "axon" not in platforms.lower()
    return _supports_complex


def supports_host_callback() -> bool:
    """Whether the default backend implements host send/recv callbacks
    (jax pure_callback / io_callback / debug.callback). Production XLA
    backends do; the axon tunnel rejects them with UNIMPLEMENTED."""
    return supports_complex()  # same capability gap, same detection


def is_compiled_with_cuda() -> bool:  # API parity; this build has zero CUDA
    return False


def device_count() -> int:
    return len(jax.devices())
