"""jit.save / jit.load — the serialized-model + inference-predictor path.

Reference analogs:
- paddle.jit.save writes ProgramDesc protobuf + params (jit/api.py,
  SURVEY §3.3.6); here `save` writes a portable serialized XLA program
  (jax.export StableHLO artifact) + a pickled numpy state dict.
- AnalysisPredictor (paddle/fluid/inference/api/analysis_predictor.h:95)
  loads a saved model and serves it with no Python source for the
  original nn.Layer; here `load` deserializes the exported program and
  returns a callable TranslatedLayer (jit::Layer analog,
  paddle/fluid/jit/layer.h).
- convert_to_mixed_precision (inference/analysis/passes/
  convert_to_mixed_precision.cc) becomes `save(..., convert="bfloat16")`:
  float params are cast to bf16 and the traced program computes in bf16,
  with float inputs/outputs cast at the boundary.

Artifacts written at {path}:
  {path}.pdiparams  pickled numpy state dict (weights)
  {path}.jaxep      serialized jax.export artifact of fn(params, *ins)
  {path}.json       metadata: input spec, param names/order, convert mode
  {path}.mlir       StableHLO text (human-inspectable, not reloaded)
"""
from __future__ import annotations

import json
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor


def _spec_to_json(spec):
    """PartitionSpec -> JSON list (None | str | [str,...] per dim)."""
    if spec is None:
        return None
    return [list(e) if isinstance(e, (tuple, list)) else e
            for e in tuple(spec)]


def spec_from_json(entry):
    """Inverse of _spec_to_json; None stays None (= replicated)."""
    from jax.sharding import PartitionSpec as P

    if entry is None:
        return None
    return P(*[tuple(e) if isinstance(e, list) else e for e in entry])


def _export_platforms():
    """Always export for cpu AND tpu: the artifact must be loadable on a
    TPU serving host even when saved from a CPU-only process (and vice
    versa for CI). jax.export lowers for both ahead of time."""
    return ["cpu", "tpu"]


def save(layer, path, input_spec=None, convert=None, **configs):
    """Serialize layer weights + (if input_spec given) an executable
    exported program.

    convert: None | "bfloat16" — mixed-precision convert at save time:
    float params are stored and traced in bf16 (float inputs are cast in,
    float outputs cast back to fp32 at the boundary).
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    from paddle_tpu.nn.layer import Layer

    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer")

    def conv_arr(a):
        a = np.asarray(a)
        if convert == "bfloat16" and a.dtype in (np.float32, np.float64):
            return a.astype(jnp.bfloat16)
        return a

    meta = {"format": "paddle_tpu.jit.v2", "convert": convert}
    state = {k: conv_arr(v._array) for k, v in layer.state_dict().items()}
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f)

    if input_spec is not None:
        params = layer.parameters()
        buffers = list(layer.buffers())
        all_state = params + buffers
        # name order for rebinding at load time
        name_of = {id(v): k for k, v in layer.state_dict().items()}
        state_names = [name_of.get(id(t)) for t in all_state]
        if any(n is None for n in state_names):
            raise ValueError("all parameters/buffers must appear in "
                             "state_dict() to be exportable")

        # inference program: no dropout, BN in eval mode. Save/restore the
        # PER-SUBLAYER flags (a frozen-backbone model legitimately mixes
        # train/eval sublayers) and restore even if export fails.
        sub_modes = [(l, l.training) for l in layer.sublayers(include_self=True)]
        layer.eval()

        def pure_fn(state_arrays, *inputs):
            originals = [t._array for t in all_state]
            try:
                for t, a in zip(all_state, state_arrays):
                    t._array = a
                ins = []
                for i in inputs:
                    if convert == "bfloat16" and jnp.issubdtype(i.dtype, jnp.floating):
                        i = i.astype(jnp.bfloat16)
                    ins.append(Tensor._wrap(i))
                out = layer(*ins)

                def leaf(t):
                    a = t._array if isinstance(t, Tensor) else t
                    if convert == "bfloat16" and a.dtype == jnp.bfloat16:
                        a = a.astype(jnp.float32)
                    return a

                return jax.tree_util.tree_map(
                    leaf, out, is_leaf=lambda t: isinstance(t, Tensor))
            finally:
                for t, o in zip(all_state, originals):
                    t._array = o

        state_args = [jnp.asarray(state[n]) for n in state_names]
        from jax import export as jax_export

        # None/-1 dims become symbolic (jax.export shape polymorphism):
        # the loaded predictor then accepts any size there (the dynamic-
        # batch contract of paddle.static.InputSpec). A string dim names
        # its symbol, so specs can SHARE a dimension (e.g. two inputs
        # with the same "batch") — unnamed dims are independent symbols.
        # All symbols must live in ONE scope, so they are created in a
        # single symbolic_shape call and distributed by name.
        user_names = {d for s in input_spec for d in s.shape
                      if isinstance(d, str)}
        auto_names = iter(n for i in range(10000)
                          if (n := f"_b{i}") not in user_names)
        # per-dim resolved name (None = static), computed once so both
        # the symbol-scope pass and the shape pass agree
        dim_names = [[d if isinstance(d, str)
                      else next(auto_names)
                      if d is None or (isinstance(d, int) and d < 0)
                      else None
                      for d in s.shape] for s in input_spec]
        names = []
        for row in dim_names:
            for n in row:
                if n is not None and n not in names:
                    names.append(n)
        syms = dict(zip(names, jax_export.symbolic_shape(",".join(names)))) \
            if names else {}
        example = []
        for s, row in zip(input_spec, dim_names):
            dims = [d if n is None else syms[n]
                    for d, n in zip(s.shape, row)]
            dt = s.dtype if isinstance(s.dtype, str) else "float32"
            example.append(jax.ShapeDtypeStruct(tuple(dims), jnp.dtype(dt)))

        try:
            exported = jax_export.export(
                jax.jit(pure_fn), platforms=_export_platforms())(
                    state_args, *example)
        finally:
            for l, mode in sub_modes:
                l.training = mode
        with open(path + ".jaxep", "wb") as f:
            f.write(exported.serialize())
        with open(path + ".mlir", "w") as f:
            # the Exported already holds the StableHLO — no second trace
            f.write(str(exported.mlir_module()))
        meta["input_spec"] = [
            {"shape": list(s.shape), "dtype": str(s.dtype),
             "name": getattr(s, "name", None)} for s in input_spec
        ]
        meta["state_names"] = state_names
        # layer-level weight shardings (mp layers set Tensor.dist_spec,
        # e.g. ColumnParallelLinear -> P(None, 'mp')): recorded so a
        # saved artifact can be served tensor-parallel
        # (inference.Config.set_dist_degrees(dp, mp) — the
        # dist_model.cc multi-rank serving analog)
        meta["state_dist_specs"] = [
            _spec_to_json(getattr(t, "dist_spec", None))
            for t in all_state]
        meta["has_mlir"] = True
        meta["platforms"] = _export_platforms()

    with open(path + ".json", "w") as f:
        json.dump(meta, f)


class TranslatedLayer:
    """Loaded, executable model — the TranslatedLayer / C++ jit::Layer /
    AnalysisPredictor analog. Runs the saved XLA program with the saved
    weights; no original Python source needed."""

    def __init__(self, path, state, meta, exported=None):
        self._path = path
        self._state = state
        self._meta = meta
        if exported is not None and "state_names" not in meta:
            raise ValueError(
                f"{path}.jaxep found but {path}.json is missing or predates "
                f"format v2 — copy the full artifact set ({path}.json, "
                f".jaxep, .pdiparams) or re-save with this version")
        self._exported = exported
        if exported is not None:
            names = meta["state_names"]
            self._state_args = [jnp.asarray(state[n]) for n in names]

    @property
    def input_spec(self):
        return self._meta.get("input_spec")

    def __call__(self, *inputs):
        if self._exported is None:
            raise RuntimeError(
                f"{self._path} was saved without input_spec — no executable "
                f"program; re-save with jit.save(layer, path, input_spec=[...])")
        arrs = [i._array if isinstance(i, Tensor) else jnp.asarray(i)
                for i in inputs]
        out = self._exported.call(self._state_args, *arrs)
        return jax.tree_util.tree_map(
            lambda a: Tensor._wrap(a) if isinstance(a, jax.Array) else a, out)

    forward = __call__

    def state_dict(self):
        return {k: Tensor(v) for k, v in self._state.items()}

    def set_state_dict(self, state_dict):
        """Swap weights (same shapes) without retracing. Honors the
        artifact's convert mode: fp32 weights swapped into a
        convert="bfloat16" predictor are cast to match the program."""
        conv = self._meta.get("convert")
        for k, v in state_dict.items():
            a = v._array if isinstance(v, Tensor) else jnp.asarray(v)
            a = np.asarray(a)
            if conv == "bfloat16" and a.dtype in (np.float32, np.float64):
                a = a.astype(jnp.bfloat16)
            self._state[k] = a
        if self._exported is not None:
            self._state_args = [jnp.asarray(self._state[n])
                                for n in self._meta["state_names"]]

    def load_into(self, layer):
        layer.set_state_dict(self._state)
        return layer


def load(path, **configs):
    """Load a saved model. Returns an executable TranslatedLayer when the
    model was saved with input_spec (deserializes + compiles the exported
    program); otherwise a weights-only TranslatedLayer usable via
    load_into()."""
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    meta = {}
    if os.path.exists(path + ".json"):
        with open(path + ".json") as f:
            meta = json.load(f)
    exported = None
    if os.path.exists(path + ".jaxep"):
        from jax import export as jax_export

        with open(path + ".jaxep", "rb") as f:
            exported = jax_export.deserialize(f.read())
    return TranslatedLayer(path, state, meta, exported)
