"""jit.save / jit.load — serialized-model analog.

Reference: paddle.jit.save writes ProgramDesc protobuf + params
(jit/api.py, SURVEY §3.3.6); we serialize StableHLO text for each traced
concrete function plus a state_dict of weights. Loading returns a
TranslatedLayer-analog that compiles the StableHLO back through jax.
"""
from __future__ import annotations

import json
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor


def save(layer, path, input_spec=None, **configs):
    """Serialize layer weights + (if traceable) a StableHLO module.

    Writes: {path}.pdiparams (pickled numpy state dict),
            {path}.json (metadata), {path}.mlir (StableHLO, if input_spec).
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    from paddle_tpu.nn.layer import Layer

    meta = {"format": "paddle_tpu.jit.v1"}
    if isinstance(layer, Layer):
        state = {k: np.asarray(v._array) for k, v in layer.state_dict().items()}
        with open(path + ".pdiparams", "wb") as f:
            pickle.dump(state, f)
        if input_spec is not None:
            from .api import InputSpec

            params = layer.parameters()
            param_arrays = [p._array for p in params]

            def pure_fn(param_arrays, *inputs):
                originals = [p._array for p in params]
                try:
                    for p, a in zip(params, param_arrays):
                        p._array = a
                    out = layer(*[Tensor._wrap(i) for i in inputs])
                    return jax.tree_util.tree_map(
                        lambda t: t._array if isinstance(t, Tensor) else t, out,
                        is_leaf=lambda t: isinstance(t, Tensor))
                finally:
                    for p, o in zip(params, originals):
                        p._array = o

            example = [
                jnp.zeros(tuple(d if d and d > 0 else 1 for d in s.shape),
                          dtype=s.dtype if isinstance(s.dtype, str) else "float32")
                for s in input_spec
            ]
            lowered = jax.jit(pure_fn).lower(param_arrays, *example)
            mlir_text = lowered.as_text(dialect="stablehlo")
            with open(path + ".mlir", "w") as f:
                f.write(mlir_text)
            meta["input_spec"] = [
                {"shape": list(s.shape), "dtype": str(s.dtype)} for s in input_spec
            ]
            meta["has_mlir"] = True
        with open(path + ".json", "w") as f:
            json.dump(meta, f)
    else:
        raise TypeError("jit.save expects a Layer")


class TranslatedLayer:
    """Analog of paddle.jit.TranslatedLayer: a loaded, executable model."""

    def __init__(self, path, state):
        self._path = path
        self._state = state

    def state_dict(self):
        return {k: Tensor(v) for k, v in self._state.items()}

    def load_into(self, layer):
        layer.set_state_dict(self._state)
        return layer


def load(path, **configs):
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    return TranslatedLayer(path, state)
