"""dy2static — data-dependent Python control flow under to_static.

Reference analog: python/paddle/jit/dy2static/ (program_translator.py
AST pipeline, convert_operators.py convert_ifelse/convert_while_loop).
The reference rewrites `if`/`while` whose conditions are Tensors into
cond/while ops inside the ProgramDesc; here the same AST rewrite targets
jax: conditions that turn out to be TRACED arrays run as lax.cond /
lax.while_loop (compiler-friendly, both branches staged), while plain
Python bools keep exact Python semantics via runtime dispatch — one
transform serves eager calls, jit.to_static, and TrainStep tracing.

Scope: if/elif/else and while whose bodies assign local names, and
branches that both return. Constructs outside it (break/continue,
one-sided returns, while/else) keep Python semantics — correct for bool
conditions, and a Tensor condition then fails loudly at bool(tracer)
rather than silently changing control flow. Reverse-mode AD through a
tensor-`while` is a JAX limit (lax.while_loop is not transposable) —
training through one raises jax's precise error; tensor-`if` (lax.cond)
differentiates fine.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["convert_ifelse", "convert_while_loop", "convert_for_loop",
           "transform_function", "UNDEF"]


class _Undefined:
    """Sentinel for names defined in only some branches (the reference's
    UndefinedVar)."""

    def __repr__(self):
        return "<undefined>"

    def __bool__(self):
        raise NameError("variable is undefined on this branch")


UNDEF = _Undefined()


def init_undef(thunk):
    """`x = _paddle_jst.init_undef(lambda: x)` — UNDEF when x is not yet bound."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return UNDEF


def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


def _cond_value(cond):
    if isinstance(cond, Tensor):
        cond = cond._array
    return cond


def _wrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda a: Tensor._wrap(a) if isinstance(a, (jax.Array,)) or
        _is_traced(a) else a, tree)


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda t: t._array if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda t: isinstance(t, Tensor))


def _is_carried(x, numbers=False):
    """Can x ride a lax.cond/while_loop operand? UNDEF and arbitrary
    python objects travel by closure instead."""
    import numpy as _np

    if isinstance(x, (Tensor, jax.Array, _np.ndarray)) or _is_traced(x):
        return True
    return numbers and isinstance(x, (bool, int, float))


def _unwrap_one(x):
    if isinstance(x, Tensor):
        return x._array
    return x


def _wrap_one(x):
    if isinstance(x, (jax.Array,)) or _is_traced(x):
        return Tensor._wrap(x)
    return x


def convert_ifelse(cond, true_fn, false_fn, args):
    """convert_operators.convert_ifelse analog. `args` is the tuple of
    branch-carried locals; both fns take and return that tuple."""
    v = _cond_value(cond)
    if not _is_traced(v):
        return true_fn(*args) if bool(v) else false_fn(*args)

    # traced: stage BOTH branches as lax.cond. Array-like vars cross the
    # boundary as the operand; UNDEF / python values go by closure (a
    # branch assigning them puts the new value in the OUTPUT tree, which
    # lax.cond checks for cross-branch agreement).
    dyn = [i for i, a in enumerate(args) if _is_carried(a)]
    template = list(args)

    def stage(fn):
        def staged(operand):
            full = list(template)
            for i, a in zip(dyn, operand):
                full[i] = _wrap_one(a)
            return _unwrap_tree(tuple(fn(*full)))
        return staged

    operand = tuple(_unwrap_one(args[i]) for i in dyn)
    try:
        out = jax.lax.cond(jnp.asarray(v).astype(bool),
                           stage(true_fn), stage(false_fn), operand)
    except TypeError as e:
        raise TypeError(
            "tensor-dependent `if`: both branches must produce the same "
            f"variables with matching shapes/dtypes ({e})") from e
    return tuple(_wrap_one(o) for o in out)


def convert_while_loop(cond_fn, body_fn, args):
    """convert_operators.convert_while_loop analog."""
    v = _cond_value(cond_fn(*args))
    if not _is_traced(v):
        # eager: plain python loop (each iteration re-evaluates concretely)
        while bool(_cond_value(cond_fn(*args))):
            args = body_fn(*args)
        return args

    # numbers must join the carry: loop counters evolve across iterations
    dyn = [i for i, a in enumerate(args) if _is_carried(a, numbers=True)]
    template = list(args)

    def rebuild(operand):
        full = list(template)
        for i, a in zip(dyn, operand):
            full[i] = _wrap_one(a)
        return full

    def c(operand):
        return jnp.asarray(_cond_value(cond_fn(*rebuild(operand)))) \
            .astype(bool)

    def b(operand):
        out = tuple(body_fn(*rebuild(operand)))
        return tuple(_unwrap_one(out[i]) for i in dyn)

    operand = tuple(jnp.asarray(_unwrap_one(args[i])) for i in dyn)
    try:
        out = jax.lax.while_loop(c, b, operand)
    except TypeError as e:
        raise TypeError(
            "tensor-dependent `while`: loop variables must keep the same "
            f"shapes/dtypes across iterations ({e})") from e
    full = rebuild(out)
    return tuple(full)


class _RangeSpec:
    """AST-detected `range(...)` iterable: bounds may be Tensors (the
    reference's convert_range), so python range() must not see them."""

    def __init__(self, *args):
        if len(args) == 1:
            self.start, self.stop, self.step = 0, args[0], 1
        elif len(args) == 2:
            (self.start, self.stop), self.step = args, 1
        else:
            self.start, self.stop, self.step = args


def _range_cond(i, stop, step):
    """Direction-aware bound check, traceable (operands may arrive as
    Tensors re-wrapped by the while-loop carry)."""
    i, stop, step = (_unwrap_one(x) for x in (i, stop, step))
    if _is_traced(i) or _is_traced(stop) or _is_traced(step):
        up = jnp.asarray(i) < jnp.asarray(stop)
        down = jnp.asarray(i) > jnp.asarray(stop)
        return jnp.where(jnp.asarray(step) > 0, up, down)
    return i < stop if step > 0 else i > stop


def convert_for_loop(iterable, body_fn, args, target_idx=None):
    """convert_operators convert_for_loop/convert_range analog.
    body_fn(item, *vars) -> vars. Three runtime forms:
    - range with Tensor/traced bounds -> counter-carried lax.while_loop
      (through convert_while_loop — the data-dependent decode-loop
      path);
    - Tensor/array iteration over axis 0 -> same loop with a
      dynamic_index item (static python n, traced index);
    - anything else (python range, lists, generators) -> exact python
      iteration.

    target_idx: position of a simple loop target within `args` — on the
    traced paths the carry can't carry an initially-UNDEF target, so
    its post-loop value is reconstructed from the counter (python
    leaves the last item bound after the loop). A zero-trip traced
    range leaves `start - step` there rather than python's unbound
    (code reading the target of a loop that never ran is broken either
    way)."""
    if isinstance(iterable, _RangeSpec):
        start, stop, step = (_unwrap_one(_cond_value(x))
                             for x in (iterable.start, iterable.stop,
                                       iterable.step))
        if not any(map(_is_traced, (start, stop, step))):
            for i in range(int(start), int(stop), int(step)):
                args = tuple(body_fn(i, *args))
            return args

        def cond_fn(i, *vs):
            return _range_cond(i, stop, step)

        def body2(i, *vs):
            out = tuple(body_fn(_wrap_one(i), *vs))
            return (_unwrap_one(i) + step,) + out

        out = convert_while_loop(cond_fn, body2,
                                 (jnp.asarray(start),) + tuple(args))
        final = list(out[1:])
        if target_idx is not None:
            final[target_idx] = _wrap_one(_unwrap_one(out[0]) - step)
        return tuple(final)

    arr = _unwrap_one(iterable) if isinstance(iterable, Tensor) \
        else iterable
    if isinstance(arr, jax.Array) or _is_traced(arr):
        n = arr.shape[0]  # leading dim is static under jax
        if not _is_traced(arr):
            for i in range(n):
                args = tuple(body_fn(_wrap_one(arr[i]), *args))
            return args

        def cond_fn(i, *vs):
            return i < n

        def body2(i, *vs):
            item = jax.lax.dynamic_index_in_dim(arr, _unwrap_one(i),
                                                keepdims=False)
            out = tuple(body_fn(_wrap_one(item), *vs))
            return (_unwrap_one(i) + 1,) + out

        out = convert_while_loop(cond_fn, body2,
                                 (jnp.asarray(0),) + tuple(args))
        final = list(out[1:])
        if target_idx is not None and n > 0:
            final[target_idx] = _wrap_one(arr[n - 1])
        return tuple(final)

    # plain python iterable: exact python semantics
    for item in iterable:
        args = tuple(body_fn(item, *args))
    return args


# ---------------------------------------------------------------------------
# the AST transform (program_translator / ifelse_transformer analog)
# ---------------------------------------------------------------------------
class _AssignedNames(ast.NodeVisitor):
    """Names bound by a statement list (branch-carried variables)."""

    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        # local defs (incl. generated __jst_* helpers) can't cross a
        # lax.cond boundary; they stay branch-local — don't descend
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


def _contains(stmts, kinds):
    """Like ast.walk but stops at nested function/lambda boundaries, so a
    `return` inside a local def doesn't count as a branch return."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, kinds):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return False


def _all_paths_return(stmts):
    return bool(stmts) and isinstance(stmts[-1], ast.Return)


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0

    def _name(self, base):
        self.counter += 1
        return f"__jst_{base}_{self.counter}"

    # -- if ---------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        if _contains(node.body + node.orelse, (ast.Break, ast.Continue)):
            return node  # loop-control of an enclosing python loop
        if _all_paths_return(node.body) and node.orelse and \
                _all_paths_return(node.orelse):
            return self._rewrite_returning_if(node)
        if _contains(node.body + node.orelse, (ast.Return,)):
            # one-sided/mid-branch return: keep python semantics (fails
            # loudly under trace — bool() on a tracer — rather than
            # silently changing control flow)
            return node
        return self._rewrite_assigning_if(node)

    def _branch_fn(self, name, stmts, vars_):
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=v) for v in vars_],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=v, ctx=ast.Load()) for v in vars_],
            ctx=ast.Load()))
        return ast.FunctionDef(name=name, args=args,
                               body=list(stmts) + [ret],
                               decorator_list=[], returns=None)

    def _rewrite_assigning_if(self, node):
        vars_ = sorted(_assigned(node.body) | _assigned(node.orelse))
        tname, fname = self._name("true"), self._name("false")
        out = []
        # seed possibly-unbound carried vars with UNDEF
        for v in vars_:
            out.append(ast.parse(
                f"{v} = _paddle_jst.init_undef(lambda: {v})").body[0])
        out.append(self._branch_fn(tname, node.body, vars_))
        out.append(self._branch_fn(
            fname, node.orelse or [ast.Pass()], vars_))
        call = ast.Call(
            func=ast.Attribute(value=ast.Name(id="_paddle_jst", ctx=ast.Load()),
                               attr="convert_ifelse", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=v, ctx=ast.Load())
                                  for v in vars_], ctx=ast.Load())],
            keywords=[])
        if vars_:
            tgt = ast.Tuple(elts=[ast.Name(id=v, ctx=ast.Store())
                                  for v in vars_], ctx=ast.Store()) \
                if len(vars_) > 1 else ast.Name(id=vars_[0], ctx=ast.Store())
            out.append(ast.Assign(
                targets=[tgt],
                value=call if len(vars_) > 1 else
                ast.Subscript(value=call,
                              slice=ast.Constant(value=0), ctx=ast.Load())))
        else:
            out.append(ast.Expr(value=call))
        return out

    def _rewrite_returning_if(self, node):
        tname, fname = self._name("true"), self._name("false")

        class _TupleReturns(ast.NodeTransformer):
            def visit_FunctionDef(self, n):
                return n  # don't descend

            visit_AsyncFunctionDef = visit_FunctionDef
            visit_Lambda = visit_FunctionDef

            def visit_Return(self, n):
                val = n.value or ast.Constant(value=None)
                return ast.Return(value=ast.Tuple(elts=[val],
                                                  ctx=ast.Load()))

        def as_fn(name, stmts):
            stmts = [_TupleReturns().visit(s) for s in stmts]
            args = ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                 kw_defaults=[], defaults=[])
            return ast.FunctionDef(name=name, args=args, body=list(stmts),
                                   decorator_list=[], returns=None)

        call = ast.Call(
            func=ast.Attribute(value=ast.Name(id="_paddle_jst", ctx=ast.Load()),
                               attr="convert_ifelse", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  ast.Tuple(elts=[], ctx=ast.Load())],
            keywords=[])
        ret = ast.Return(value=ast.Subscript(
            value=call, slice=ast.Constant(value=0), ctx=ast.Load()))
        return [as_fn(tname, node.body), as_fn(fname, node.orelse), ret]

    # -- for --------------------------------------------------------------
    @staticmethod
    def _target_names(target):
        v = _AssignedNames()
        v.visit(target)
        return v.names

    def visit_For(self, node):
        self.generic_visit(node)
        if node.orelse:
            return node  # for/else keeps python semantics
        if _contains(node.body, (ast.Break, ast.Continue, ast.Return)):
            return node  # loop-control/return: python semantics
        tgt_names = self._target_names(node.target)
        vars_ = sorted(_assigned(node.body) | tgt_names)
        if not vars_:
            return node
        bname = self._name("forbody")
        item = self._name("item")
        out = []
        for v in vars_:
            out.append(ast.parse(
                f"{v} = _paddle_jst.init_undef(lambda: {v})").body[0])
        # body fn: (item, *vars) -> (*vars,); first stmt unpacks the
        # loop target from item
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=item)] + [ast.arg(arg=v) for v in vars_],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        unpack = ast.Assign(targets=[node.target],
                            value=ast.Name(id=item, ctx=ast.Load()))
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=v, ctx=ast.Load()) for v in vars_],
            ctx=ast.Load()))
        out.append(ast.FunctionDef(
            name=bname, args=args,
            body=[unpack] + list(node.body) + [ret],
            decorator_list=[], returns=None))
        # range(...) detected at AST level: bounds may be Tensors, so
        # python range() must never see them (_RangeSpec carries them)
        it = node.iter
        if isinstance(it, ast.Call) and \
                isinstance(it.func, ast.Name) and it.func.id == "range" \
                and not it.keywords:
            iter_expr = ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id="_paddle_jst", ctx=ast.Load()),
                    attr="_RangeSpec", ctx=ast.Load()),
                args=list(it.args), keywords=[])
        else:
            iter_expr = it
        # a simple-name target's position lets the runtime reconstruct
        # its post-loop value on traced paths
        tgt_idx = vars_.index(node.target.id) \
            if isinstance(node.target, ast.Name) else None
        call = ast.Call(
            func=ast.Attribute(value=ast.Name(id="_paddle_jst",
                                              ctx=ast.Load()),
                               attr="convert_for_loop", ctx=ast.Load()),
            args=[iter_expr,
                  ast.Name(id=bname, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=v, ctx=ast.Load())
                                  for v in vars_], ctx=ast.Load())],
            keywords=[ast.keyword(arg="target_idx",
                                  value=ast.Constant(value=tgt_idx))])
        tgt = ast.Tuple(elts=[ast.Name(id=v, ctx=ast.Store())
                              for v in vars_], ctx=ast.Store()) \
            if len(vars_) > 1 else ast.Name(id=vars_[0], ctx=ast.Store())
        out.append(ast.Assign(
            targets=[tgt],
            value=call if len(vars_) > 1 else
            ast.Subscript(value=call, slice=ast.Constant(value=0),
                          ctx=ast.Load())))
        return out

    # -- while ------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse:
            return node  # while/else: python semantics
        if _contains(node.body, (ast.Break, ast.Continue, ast.Return)):
            # python semantics; a Tensor condition then fails loudly at
            # bool(tracer) instead of silently changing control flow
            return node
        vars_ = sorted(_assigned(node.body))
        if not vars_:
            return node
        cname, bname = self._name("cond"), self._name("body")
        out = []
        for v in vars_:
            out.append(ast.parse(
                f"{v} = _paddle_jst.init_undef(lambda: {v})").body[0])
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=v) for v in vars_],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        out.append(ast.FunctionDef(
            name=cname, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[],
            returns=None))
        out.append(self._branch_fn(bname, node.body, vars_))
        call = ast.Call(
            func=ast.Attribute(value=ast.Name(id="_paddle_jst", ctx=ast.Load()),
                               attr="convert_while_loop", ctx=ast.Load()),
            args=[ast.Name(id=cname, ctx=ast.Load()),
                  ast.Name(id=bname, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=v, ctx=ast.Load())
                                  for v in vars_], ctx=ast.Load())],
            keywords=[])
        tgt = ast.Tuple(elts=[ast.Name(id=v, ctx=ast.Store())
                              for v in vars_], ctx=ast.Store()) \
            if len(vars_) > 1 else ast.Name(id=vars_[0], ctx=ast.Store())
        out.append(ast.Assign(
            targets=[tgt],
            value=call if len(vars_) > 1 else
            ast.Subscript(value=call, slice=ast.Constant(value=0),
                          ctx=ast.Load())))
        return out


def transform_function(fn):
    """Rewrite fn's if/while into convert_* calls. Returns the original
    on anything untransformable (source unavailable, exotic constructs) —
    the reference's fallback-to-original behavior."""
    raw = getattr(fn, "__func__", fn)
    try:
        src = textwrap.dedent(inspect.getsource(raw))
    except (OSError, TypeError):
        return fn
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    if not _contains(fdef.body, (ast.If, ast.While, ast.For)):
        return fn
    fdef.decorator_list = []
    _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(tree)

    # re-exec inside a factory that rebinds the original free variables
    freevars = raw.__code__.co_freevars
    factory_name = "__jst_factory"
    factory = ast.FunctionDef(
        name=factory_name,
        args=ast.arguments(posonlyargs=[],
                           args=[ast.arg(arg=v) for v in freevars],
                           kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=[fdef, ast.Return(value=ast.Name(id=fdef.name,
                                              ctx=ast.Load()))],
        decorator_list=[], returns=None)
    mod = ast.fix_missing_locations(ast.Module(body=[factory],
                                               type_ignores=[]))
    import paddle_tpu.jit.dy2static as _jst_mod

    # exec against the LIVE module globals (not a snapshot) so later
    # rebinding of module-level names stays visible to the transformed
    # function; only the prefixed helper binding is added
    glb = raw.__globals__
    glb["_paddle_jst"] = _jst_mod
    try:
        code = compile(mod, filename=f"<dy2static {raw.__name__}>",
                       mode="exec")
        exec(code, glb)
        cells = [c.cell_contents for c in (raw.__closure__ or ())]
        new = glb.pop(factory_name)(*cells)
    except Exception:
        glb.pop(factory_name, None)
        return fn  # transform must never break a function that ran before
    functools.update_wrapper(new, raw)
    new.__jst_transformed__ = True
    if inspect.ismethod(fn):
        return new.__get__(fn.__self__, type(fn.__self__))
    return new
