"""Trace-machinery introspection metadata — the jit layer's own
description of which APIs stage python callables into XLA programs,
which call keywords mark arguments static or donated, and which
sibling-module calls are host-blocking when issued under a trace.

This module is deliberately PURE DATA (no jax import, no framework
import): `paddle_tpu.analysis` (tpu-lint) reads it to resolve
jit-reachability and donation statically, and `jit.api` consumes the
donation constants for its own `jax.jit(..., donate_argnums=...)`
calls — one source of truth instead of the analyzer string-matching
the framework's internals.

Names are CANONICAL dotted paths as the analyzer resolves them through
import aliases (`import jax.numpy as jnp` resolves `jnp.matmul` to
`jax.numpy.matmul`).
"""
from __future__ import annotations

# ---------------------------------------------------------------------------
# Trace entry points
# ---------------------------------------------------------------------------

#: Decorators that make the decorated function a traced program.
#: Maps canonical name -> "kind". Kind "dy2static" means the wrapper
#: runs the dy2static AST pass first, so python `if`/`while` on traced
#: booleans are converted to lax.cond/while_loop (TPU002 exempts the
#: directly-wrapped function body; its callees are NOT transformed).
TRACE_DECORATORS = {
    "jax.jit": "jit",
    "jax.pmap": "jit",
    "paddle_tpu.jit.to_static": "dy2static",
    "paddle_tpu.jit.api.to_static": "dy2static",
}

#: Callables that stage a python-callable ARGUMENT into traced code.
#: Maps canonical name -> tuple of traced-callable positional indices.
#: For jax.lax.switch the branch list at index 1 is a sequence of
#: callables (the analyzer unpacks list/tuple literals at any traced
#: position).
TRACING_CALLABLES = {
    "jax.jit": (0,),
    "jax.pmap": (0,),
    "jax.vmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.jacfwd": (0,),
    "jax.jacrev": (0,),
    "jax.hessian": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "jax.eval_shape": (0,),
    "jax.make_jaxpr": (0,),
    "jax.lax.scan": (0,),
    "jax.lax.map": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": (1,),
    "jax.lax.associative_scan": (0,),
    "jax.custom_vjp": (0,),
    "jax.custom_jvp": (0,),
    "jax.experimental.pallas.pallas_call": (0,),
    "jax.experimental.shard_map.shard_map": (0,),
    "paddle_tpu.jit.to_static": (0,),
    "paddle_tpu.jit.api.to_static": (0,),
}

#: The subset of TRACING_CALLABLES / TRACE_DECORATORS that accept
#: static/donate keywords (jit-like signatures).
JIT_LIKE = {"jax.jit", "jax.pmap"}

#: Wrappers that return their first argument's callable semantics
#: unchanged — `jax.jit(count_traces(f))` traces f. The analyzer
#: stages through them.
PASSTHROUGH_WRAPPERS = {
    "paddle_tpu.jit.count_traces",
    "paddle_tpu.jit.api.count_traces",
    "functools.partial",
    "functools.wraps",
}

#: Call keywords that mark arguments STATIC (python values re-traced
#: per value, never tracers) and DONATED (buffer invalidated by the
#: call).
STATIC_ARG_KEYWORDS = ("static_argnums", "static_argnames")
DONATE_ARG_KEYWORDS = ("donate_argnums", "donate_argnames")

#: Decorator marking a function explicitly NOT traced
#: (paddle_tpu.jit.not_to_static).
NOT_TRACED_DECORATORS = {
    "paddle_tpu.jit.not_to_static",
    "paddle_tpu.jit.api.not_to_static",
}

# ---------------------------------------------------------------------------
# Donation layout of the framework's own compiled steps
# ---------------------------------------------------------------------------

#: jit.TrainStep donates (param_arrays, accums, bufs) — the first three
#: positional arguments of every step/scan/repeat program — so the
#: optimizer update happens in-place in HBM. The accumulate path's
#: acc_fn donates only its grad buffers (position 0).
TRAINSTEP_DONATE_ARGNUMS = (0, 1, 2)
ACCUM_DONATE_ARGNUMS = (0,)

#: The serving engine's compiled steps all share ONE donation layout:
#: every step body is `(state_arrays, kpool, vpool, *host_args)` and
#: donates the two pool planes (positions 1, 2) so XLA updates the
#: paged KV cache in place in HBM. The copy-on-write block-copy step
#: is `(kpool, vpool, src, dst)` and donates positions 0, 1.
ENGINE_STEP_DONATE_ARGNUMS = (1, 2)
ENGINE_COW_DONATE_ARGNUMS = (0, 1)

#: Donation layout of EVERY compiled engine program, by program name
#: (the `__name__` the engine assigns each step body). This is the one
#: source of truth both analyzers read: tpu-lint TPU004 resolves
#: `donate_argnums=introspect.<NAME>` expressions through
#: DONATION_CONSTANTS below, and tpu-verify TPU101 checks that the
#: argnums declared HERE produce real input/output aliases in each
#: program's lowered module — no magic `(1, 2)` literals anywhere.
ENGINE_STEP_DONATION = {
    "engine_prefill": ENGINE_STEP_DONATE_ARGNUMS,
    "engine_prefill_chunk": ENGINE_STEP_DONATE_ARGNUMS,
    "engine_decode_step": ENGINE_STEP_DONATE_ARGNUMS,
    "engine_verify_step": ENGINE_STEP_DONATE_ARGNUMS,
    "engine_cow_copy": ENGINE_COW_DONATE_ARGNUMS,
}

#: Named donation layouts by constant name — TPU004 resolves a
#: `donate_argnums=introspect.<NAME>` expression through this table,
#: so the framework's own jit sites stay visible to the rule.
DONATION_CONSTANTS = {
    "TRAINSTEP_DONATE_ARGNUMS": TRAINSTEP_DONATE_ARGNUMS,
    "ACCUM_DONATE_ARGNUMS": ACCUM_DONATE_ARGNUMS,
    "ENGINE_STEP_DONATE_ARGNUMS": ENGINE_STEP_DONATE_ARGNUMS,
    "ENGINE_COW_DONATE_ARGNUMS": ENGINE_COW_DONATE_ARGNUMS,
}

# ---------------------------------------------------------------------------
# Host-sync / side-effect surfaces (TPU001 / TPU005)
# ---------------------------------------------------------------------------

#: Method names that force a device->host transfer of their receiver.
#: `.numpy()` is this framework's Tensor sync (core.tensor.Tensor).
HOST_SYNC_METHODS = ("item", "tolist", "numpy")

#: Free functions that concretize a traced value on host.
HOST_SYNC_CALLS = {
    "numpy.asarray",
    "numpy.array",
    "jax.device_get",
}

#: Builtins that concretize a traced scalar (bool-coercion hazards are
#: TPU002's domain — branches are where they bite).
HOST_SYNC_BUILTINS = ("float", "int")

#: Wall-clock / python-RNG calls that are side effects under trace:
#: they execute ONCE at trace time and bake a constant into the
#: compiled program.
IMPURE_CALLS = {
    "time.time",
    "time.time_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.monotonic",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "random.random",
    "random.randint",
    "random.uniform",
    "random.choice",
    "random.shuffle",
    "random.sample",
}

#: Module prefixes whose calls are impure under trace (numpy's global
#: RNG draws a host value at trace time).
IMPURE_CALL_PREFIXES = ("numpy.random.",)

# ---------------------------------------------------------------------------
# PRNG key discipline (TPU003)
# ---------------------------------------------------------------------------

#: jax.random functions that DERIVE fresh keys (passing a key here does
#: not "spend" it for reuse purposes — though using the parent after a
#: plain split is still caught when the parent is sampled twice).
RANDOM_KEY_DERIVERS = ("split", "fold_in", "PRNGKey", "key", "clone",
                       "key_data", "wrap_key_data")

#: Prefixes under which a first-argument key is CONSUMED by a sampler.
RANDOM_NAMESPACES = ("jax.random.",)

# ---------------------------------------------------------------------------
# Eager collectives (TPU007)
# ---------------------------------------------------------------------------

#: paddle_tpu.distributed functions that run their OWN compiled
#: program over the mesh and block the host — calling one inside a
#: traced function either fails to trace or silently stages a nested
#: dispatch. Traced code must use mesh-level primitives
#: (jax.lax.psum / shard_map) or the spmd TrainStep shardings instead.
#: tests assert this list stays in sync with paddle_tpu.distributed's
#: public eager API.
EAGER_COLLECTIVES = (
    "all_reduce", "all_gather", "broadcast", "reduce", "scatter",
    "alltoall", "reduce_scatter", "send", "recv", "isend", "irecv",
    "batch_isend_irecv", "barrier",
)

EAGER_COLLECTIVE_PREFIXES = (
    "paddle_tpu.distributed.",
    "paddle_tpu.distributed.collective.",
)

# ---------------------------------------------------------------------------
# Dtype-widening surfaces (TPU008)
# ---------------------------------------------------------------------------

#: Contraction ops whose accumulator dtype follows the operand dtype
#: unless preferred_element_type pins it — the bf16 cancellation bug
#: class (see DESIGN_DECISIONS on the paged-attention PV fix).
CONTRACTION_CALLS = {
    "jax.numpy.matmul",
    "jax.numpy.dot",
    "jax.numpy.einsum",
    "jax.numpy.tensordot",
    "jax.lax.dot_general",
    "jax.lax.dot",
}

ACCUM_DTYPE_KEYWORD = "preferred_element_type"

# ---------------------------------------------------------------------------
# Concurrency surfaces (tpu-race, TPU2xx)
# ---------------------------------------------------------------------------

#: Canonical constructors whose result is a mutual-exclusion guard —
#: an attribute assigned from one of these (or from a name that itself
#: looks like a lock) names a LOCK in tpu-race's lock-set analysis,
#: and `with <that attribute>:` opens a guarded region.
LOCK_CONSTRUCTORS = (
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
)

#: Canonical constructor for thread-confined storage: every access
#: whose base is an attribute assigned from one of these is exempt
#: from the shared-mutable rule (the PhaseTimer discipline).
THREAD_LOCAL_CONSTRUCTORS = ("threading.local",)

#: Canonical callables that put a python callable on another thread.
#: Maps canonical name -> (keyword, positional index) locating the
#: callable argument — the seeds of tpu-race's thread-escape analysis
#: (TPU201/TPU205), mirroring how TRACING_CALLABLES seeds tpu-lint's
#: jit-reachability.
THREAD_SPAWN_CALLS = {
    "threading.Thread": ("target", 1),
    "threading.Timer": ("function", 1),
}

#: Method attribute that hands its first positional argument to an
#: executor's worker thread (concurrent.futures submit convention).
EXECUTOR_SUBMIT_METHODS = ("submit",)

#: Host-blocking calls for TPU204 (blocking-call-under-lock): the
#: canonical free functions, plus method attributes that block when
#: their receiver was built by one of BLOCKING_RECEIVER_TYPES (the
#: receiver gate keeps `",".join(...)` and `dict.get` out).
BLOCKING_CALLS = (
    "time.sleep",
    "jax.block_until_ready",
)
BLOCKING_METHODS = ("join", "get", "wait", "result", "acquire")
BLOCKING_RECEIVER_TYPES = (
    "threading.Thread",
    "threading.Event",
    "threading.Condition",
    "threading.Lock",
    "threading.RLock",
    "queue.Queue",
    "queue.SimpleQueue",
    "queue.LifoQueue",
    "queue.PriorityQueue",
)

# ---------------------------------------------------------------------------
# Async-pipeline effect table (tpu-race TPU203)
# ---------------------------------------------------------------------------
# The ENGINE_STEP_DONATION precedent, applied to the dispatch-ahead
# pipeline: the engine and the allocators DECLARE their effect surfaces
# here, the race analyzer READS them — no magic method-name strings on
# either side. Three effect classes:
#
# - DISPATCH: engine methods that issue a compiled step and return
#   WITHOUT waiting on its output (they seat an `_InFlight` record).
#   Between such a call and its completion the device may still be
#   writing into allocator-managed KV blocks / adapter pages.
# - COMPLETE: calls that synchronize outstanding device work — the
#   explicit wait plus every host materialization the serial complete
#   stages use (np.asarray IS the sync on the serial path).
# - RELEASE: allocator methods that free or recycle device-visible
#   pages. Calling one while a dispatch is outstanding is the
#   zombie-write hazard of DESIGN_DECISIONS r21 — the reason the
#   async pipe holds at depth 1.

#: Engine methods that dispatch a compiled step without waiting.
ENGINE_DISPATCH_EFFECTS = (
    "_plain_dispatch",
    "_spec_dispatch",
    "_dispatch_ahead",
)

#: Calls that complete (synchronize) outstanding dispatches.
STEP_COMPLETE_CALLS = ("jax.block_until_ready",) \
    + tuple(sorted(HOST_SYNC_CALLS))

#: Allocator release/recycle surface, by owning class. `free`/`release`
#: drop references (blocks can re-enter the pool under an in-flight
#: writer); `allocate`/`acquire` recycle evictable pages in place.
ALLOCATOR_RELEASE_EFFECTS = {
    "PagedKVCache": ("free", "allocate"),
    "PagedAdapterPool": ("release", "acquire"),
}

# ---------------------------------------------------------------------------
# Per-axis collective budget (tpu-verify TPU104 / tpu-shard TPU30x)
# ---------------------------------------------------------------------------
# The ENGINE_STEP_DONATION precedent applied to mesh collectives: ONE
# declared table carries, per (mesh axis, collective kind), the
# allowed per-transformer-layer count, the allowed fixed count, and a
# payload bound expressed over the serving geometry. tpu-verify's
# TPU104 consumes the COUNT view (per_layer/fixed/allowed — the same
# surface the old count-only CollectiveBudget exposed, so the count
# gate is unchanged by construction); tpu-shard's TPU301/304/305
# consume the AXIS view (which axis a collective may cross, how many
# bytes it may move, and whether that axis is a fast ICI link or a
# slow DCN one). Counts and bytes can never drift apart because they
# are rows of the same table.


class AxisCollectiveBudget:
    """Per-mesh-axis collective budget of ONE compiled serving step.

    axes: ((axis_name, link), ...) — every mesh axis the step may run
        collectives over, with its link class: "ici" (fast intra-slice
        interconnect) or "dcn" (slow inter-slice network; tpu-shard
        TPU305 flags per-token collectives crossing these).
    entries: ((axis, kind, per_layer, fixed, payload), ...) — per
        (axis, collective kind): the allowed per-transformer-layer
        count, the allowed fixed (embed / lm-head / whole-step) count,
        and a payload bound in BYTES as an arithmetic expression over
        the harvest geometry symbols (tokens, hidden, intermediate,
        vocab, heads, head_dim, layers, blocks, block_size, slots —
        see analysis.shard.model.eval_payload). The bound is the
        GLOBAL (post-gather / pre-reduce logical) payload, which is
        invariant to the axis size — a collective whose bytes scale
        with the mesh is exactly what TPU304 exists to catch.

    Pure data + arithmetic: no jax import, no framework import.
    """

    def __init__(self, axes=(), entries=()):
        self.axes = tuple(tuple(a) for a in axes)
        self.entries = tuple(tuple(e) for e in entries)
        links = {"ici", "dcn"}
        for _, link in self.axes:
            if link not in links:
                raise ValueError(
                    f"axis link must be one of {sorted(links)}, "
                    f"got {link!r}")
        names = set(self.axis_names())
        for axis, kind, per, fix, payload in self.entries:
            if axis not in names:
                raise ValueError(
                    f"budget entry ({axis!r}, {kind!r}) names an axis "
                    "missing from the axes table")

    def __eq__(self, other):
        return (isinstance(other, AxisCollectiveBudget)
                and self.axes == other.axes
                and self.entries == other.entries)

    def __hash__(self):
        return hash((self.axes, self.entries))

    def __repr__(self):
        return (f"AxisCollectiveBudget(axes={self.axes!r}, "
                f"entries={self.entries!r})")

    # -- count view (the CollectiveBudget surface TPU104 consumes) ----
    def _merged(self, idx):
        out = {}
        for e in self.entries:
            out[e[1]] = out.get(e[1], 0) + e[idx]
        return tuple(sorted((k, v) for k, v in out.items() if v))

    @property
    def per_layer(self):
        return self._merged(2)

    @property
    def fixed(self):
        return self._merged(3)

    def allowed(self, kind, num_layers):
        per = dict(self.per_layer).get(kind, 0)
        fix = dict(self.fixed).get(kind, 0)
        return per * num_layers + fix

    def kinds(self):
        return sorted(set(dict(self.per_layer))
                      | set(dict(self.fixed)))

    # -- axis view (tpu-shard TPU301/304/305) -------------------------
    def axis_names(self):
        return tuple(a for a, _ in self.axes)

    def link_of(self, axis):
        return dict(self.axes).get(axis)

    def slow_axes(self):
        return tuple(a for a, link in self.axes if link == "dcn")

    def entries_for(self, axis):
        return tuple(e for e in self.entries if e[0] == axis)

    def allowed_on_axis(self, axis, kind, num_layers):
        n = 0
        for _, k, per, fix, _ in self.entries_for(axis):
            if k == kind:
                n += per * num_layers + fix
        return n

    def payload_bounds(self, axis, kind):
        """Payload-bound expressions for (axis, kind), one per entry
        row — () when the kind is undeclared on that axis."""
        return tuple(e[4] for e in self.entries_for(axis)
                     if e[1] == kind)


#: Per-axis collective budget of ONE tensor-parallel GPT serving step
#: (the table `models/gpt.py:GPT_SERVING_COLLECTIVES` aliases — the
#: helpers there are the only places serving collectives come from).
#: Per transformer layer over the 'mp' (ICI) axis: _attn_out
#: all-gathers twice (head reassembly + out_proj columns) and the MLP
#: twice (fc1 + fc2 columns) = 4, each bounded by the widest gathered
#: activation (the fc1 intermediate rows); plus AT MOST one pmax when
#: the int8 KV cache is on (the quant-on-write grid fold in
#: ops/paged_attention — per-block scales are global across the
#: head-sharded pools, so the shards' absmax must agree; fp steps emit
#: zero pmax and TPU100's exact op snapshot pins that), bounded by the
#: full fp32 scale grid. Fixed: one lm-head logits all-gather
#: (tokens x vocab), one vocab-parallel-embedding psum
#: (tokens x hidden), and one pmax for the bucketed prefill's
#: whole-prompt quantized write (all layers folded in a single
#: scatter). An accidental fifth per-layer gather (or a brand-new
#: collective kind, or an axis-size-scaling payload) fails the trace
#: gates instead of stretching every decode step.
GPT_SERVING_AXIS_BUDGET = AxisCollectiveBudget(
    axes=(("mp", "ici"),),
    entries=(
        ("mp", "all_gather", 4, 0, "tokens * intermediate * 4"),
        ("mp", "all_gather", 0, 1, "tokens * vocab * 4"),
        ("mp", "psum", 0, 1, "tokens * hidden * 4"),
        ("mp", "pmax", 1, 1, "layers * blocks * 2 * 4"),
    ),
)
