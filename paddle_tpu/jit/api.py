"""jit.to_static — the dygraph→static bridge, TPU-native.

Reference analog: @paddle.jit.to_static traces python into a ProgramDesc
executed by InterpreterCore (SURVEY §3.3: program_translator.py:290 →
partial_program.py:644 → run_program op → interpretercore.cc:224).

Here the eager Tensor wraps jax arrays, so the SAME user function traces
under jax.jit directly: Tensors are wrapped around tracers, every op
flows through jnp, and the whole function lowers to ONE XLA computation.
The compile cache is keyed by input (shape, dtype) specs — the CacheKey
analog (program_translator.py:168).

`TrainStep` functionalizes a whole training step (forward + backward +
optimizer update) into one donated, jitted XLA program — the analog of
to_static over a full train loop body, and the perf path used by the
benchmarks.
"""
from __future__ import annotations

import builtins
import functools
import inspect
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from contextlib import contextmanager

from paddle_tpu.core import autograd
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit import introspect


_bound_depth = 0


def buffer_writes_captured():
    """True while a bound_state scope is live — i.e. in-trace buffer
    assignments will be captured by the compiled step's buffer plumbing
    (make_forward_loss) and then restored; layers that guard against
    tracer leaks (SpectralNorm) may write tracers freely here."""
    return _bound_depth > 0


@contextmanager
def bound_state(bind_pairs, restore_tensors):
    """Bind traced arrays into live Tensor objects for the duration of a
    trace, restoring ALL of restore_tensors after — so in-trace mutations
    (e.g. BN running stats) can't leak tracers into the eager world. The
    one bind/restore dance shared by compiled train steps and the hapi
    eval path."""
    global _bound_depth
    originals = [t._array for t in restore_tensors]
    try:
        for t, a in bind_pairs:
            t._array = a
        _bound_depth += 1
        yield
    finally:
        _bound_depth -= 1
        for t, o in zip(restore_tensors, originals):
            t._array = o


class InputSpec:
    """Analog of paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name


def _spec_of(x):
    if isinstance(x, Tensor):
        return ("T", x._array.shape, str(x._array.dtype))
    if isinstance(x, (np.ndarray, jax.Array)):
        return ("A", x.shape, str(x.dtype))
    if isinstance(x, (list, tuple)):
        return tuple(_spec_of(v) for v in x)
    return ("S", x)  # static python value — part of the cache key


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._array
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return x


def _is_arraylike(x):
    return isinstance(x, (Tensor, np.ndarray, jax.Array))


class StaticFunction:
    """Analog of dy2static StaticFunction (program_translator.py:290).

    When the traced function belongs to a Layer (decorating the layer, or
    a bound method of one), the layer's parameters AND buffers are threaded
    through the jitted program as traced arguments — so optimizer updates,
    `set_value`, `load_state_dict` etc. are visible on the next call
    instead of being baked in as compile-time constants (VERDICT r1 weak
    #1: to_static silently used stale weights). Free functions that close
    over tensors still bake them; wrap the owning Layer instead."""

    def __init__(self, fn, input_spec=None, build_strategy=None, backend=None,
                 layer=None):
        # data-dependent if/while become lax.cond/while_loop (dy2static
        # AST pass; python-bool conditions keep python semantics)
        from paddle_tpu.jit.dy2static import transform_function

        self._fn = transform_function(fn)
        self._input_spec = list(input_spec) if input_spec else None
        self._bucket_dynamic = bool(
            (build_strategy or {}).get("dynamic_dim_buckets")
            if isinstance(build_strategy, dict) else
            getattr(build_strategy, "dynamic_dim_buckets", False))
        self._layer = layer
        if layer is None and inspect.ismethod(fn):
            from paddle_tpu.nn.layer import Layer

            if isinstance(fn.__self__, Layer):
                self._layer = fn.__self__
        self._cache = {}  # spec key -> jitted callable
        functools.update_wrapper(self, fn)

    def _spec_tensors(self, args, kwargs):
        """Array-like inputs in parameter order (kwarg tensors included,
        via signature binding)."""
        if kwargs:
            try:
                ba = inspect.signature(self._fn).bind(*args, **kwargs)
                flat = list(ba.arguments.values())
            except TypeError:
                flat = list(args) + list(kwargs.values())
        else:
            flat = list(args)
        return [a for a in flat if _is_arraylike(a)]

    def _check_spec(self, args, kwargs):
        """input_spec is a contract, not a hint (program_translator.py:519
        spec-driven concretization): ranks/dtypes/fixed dims must match;
        None/-1/named dims accept any size."""
        tensors = self._spec_tensors(args, kwargs)
        if len(tensors) < len(self._input_spec):
            raise ValueError(
                f"to_static input_spec expects {len(self._input_spec)} "
                f"tensor inputs, got {len(tensors)}")
        for n, (s, a) in enumerate(zip(self._input_spec, tensors)):
            arr = a._array if isinstance(a, Tensor) else np.asarray(a)
            if len(arr.shape) != len(s.shape):
                raise ValueError(
                    f"input {n}: rank {len(arr.shape)} != input_spec rank "
                    f"{len(s.shape)} {tuple(s.shape)}")
            want = str(jnp.dtype(s.dtype if s.dtype is not None
                                 else "float32"))
            if str(arr.dtype) != want:
                raise TypeError(
                    f"input {n}: dtype {arr.dtype} != input_spec dtype "
                    f"{want}")
            for ax, d in enumerate(s.shape):
                if isinstance(d, int) and d >= 0 and arr.shape[ax] != d:
                    raise ValueError(
                        f"input {n}: dim {ax} is {arr.shape[ax]}, "
                        f"input_spec requires {d}")

    def _bucket_args(self, args, kwargs):
        """Pad AXIS-0 dynamic-spec dims up to the next power of two so N
        batch sizes share one compiled program (TPU dynamic-batch
        bucketing); outputs carrying the padded size on axis 0 are sliced
        back by the caller. Dynamic dims on other axes stay unpadded
        (each size gets its own trace). Opt-in, with two caveats: math
        that mixes rows across the batch (e.g. a mean over axis 0) sees
        the zero-pad rows, and a fixed-size output whose leading dim
        coincidentally equals the bucket size would be mis-sliced."""
        if kwargs:
            raise ValueError(
                "dynamic_dim_buckets requires the spec'd tensors to be "
                "passed positionally")
        arr_pos = [i for i, a in enumerate(args) if _is_arraylike(a)]
        args = list(args)
        orig = padded = None
        for s, i in zip(self._input_spec, arr_pos):
            if not s.shape:
                continue
            d = s.shape[0]
            if not (d is None or isinstance(d, str) or
                    (isinstance(d, int) and d < 0)):
                continue
            a = args[i]
            arr = a._array if isinstance(a, Tensor) else jnp.asarray(a)
            n = arr.shape[0]
            b = 1 << max(n - 1, 0).bit_length() if n & (n - 1) else n
            orig, padded = n, b
            if b != n:
                widths = [(0, b - n)] + [(0, 0)] * (arr.ndim - 1)
                arr = jnp.pad(arr, widths)
                args[i] = Tensor._wrap(arr) if isinstance(a, Tensor) else arr
        return tuple(args), (orig, padded) if orig is not None and \
            padded != orig else None

    @property
    def concrete_programs(self):
        return list(self._cache.values())

    def _live_state(self):
        if self._layer is None:
            return []
        return list(self._layer.parameters()) + list(self._layer.buffers())

    def __call__(self, *args, **kwargs):
        bucket = None
        if self._input_spec:
            self._check_spec(args, kwargs)
            if self._bucket_dynamic:
                args, bucket = self._bucket_args(args, kwargs)
        out = self._call_impl(args, kwargs)
        if bucket is not None:
            orig, padded = bucket

            def unslice(t):
                arr = t._array if isinstance(t, Tensor) else t
                if hasattr(arr, "shape") and arr.ndim >= 1 and \
                        arr.shape[0] == padded:
                    return t[:orig] if isinstance(t, Tensor) \
                        else arr[:orig]
                return t
            out = jax.tree_util.tree_map(
                unslice, out, is_leaf=lambda t: isinstance(t, Tensor))
        return out

    def _call_impl(self, args, kwargs):
        state = self._live_state()
        # key includes the state object identities: layer surgery that
        # REPLACES a Parameter (vs mutating it) must retrace, otherwise
        # pure_fn would bind arrays into dead objects and bake the new
        # object's value as a constant
        from paddle_tpu.framework.flags import debug_epoch

        key = (_spec_of(args), _spec_of(tuple(sorted(kwargs.items()))),
               tuple(id(t) for t in state), debug_epoch())
        entry = self._cache.get(key)
        if entry is None:
            entry = [self._build(args, kwargs, state), None]  # [jitted, tape_ok]
            self._cache[key] = entry
        jitted = entry[0]
        flat_arrays = [_unwrap(a) for a in args if _is_arraylike(a) or isinstance(a, (list, tuple))]
        kw_arrays = {k: _unwrap(v) for k, v in kwargs.items()
                     if _is_arraylike(v)}

        # Record the whole compiled program as ONE tape op so eager
        # backward flows through it into params and inputs — the analog of
        # run_program's GradNodeRunProgram (eager/to_static/
        # run_program_op_node.h). Taken for the common case: positional
        # Tensor/array args, flat Tensor(-tuple) output; anything fancier
        # falls back to no-grad wrapping.
        from paddle_tpu.core.autograd import is_grad_enabled
        from paddle_tpu.ops.dispatch import apply

        simple_args = builtins.all(
            _is_arraylike(a) or not isinstance(a, (list, tuple, dict))
            for a in args) and not kw_arrays
        tensor_args = [Tensor._wrap(jnp.asarray(a)) if not isinstance(a, Tensor) else a
                       for a in args if _is_arraylike(a)]
        if simple_args and is_grad_enabled() and any(
                not t.stop_gradient for t in state + tensor_args):
            n_state = len(state)

            def tape_fn(*all_arrays):
                return jitted(list(all_arrays[:n_state]),
                              *all_arrays[n_state:])

            if entry[1] is None:  # probe once per cache entry, not per call
                probe = jax.eval_shape(
                    tape_fn, *[t._array for t in state + tensor_args])
                leaves = probe if isinstance(probe, (tuple, list)) else [probe]
                entry[1] = builtins.all(
                    isinstance(p, jax.ShapeDtypeStruct) for p in leaves)
            if entry[1]:
                return apply(f"to_static:{getattr(self._fn, '__name__', 'fn')}",
                             tape_fn, *state, *tensor_args)

        out_arrays = jitted([t._array for t in state], *flat_arrays,
                            **kw_arrays)
        return jax.tree_util.tree_map(
            lambda a: Tensor._wrap(a) if isinstance(a, (jax.Array, jnp.ndarray)) else a,
            out_arrays)

    def _build(self, args, kwargs, state):
        fn = self._fn
        static_kwargs = {k: v for k, v in kwargs.items() if not _is_arraylike(v)}
        arr_kwarg_names = [k for k, v in kwargs.items() if _is_arraylike(v)]
        arg_templates = list(args)
        state_tensors = list(state)

        def pure_fn(state_arrays, *arrays, **akw):
            it = iter(arrays)

            def rebuild(tpl):
                if _is_arraylike(tpl):
                    return Tensor._wrap(next(it), stop_gradient=getattr(tpl, "stop_gradient", True))
                if isinstance(tpl, (list, tuple)):
                    return type(tpl)(rebuild(v) for v in tpl)
                return tpl

            new_args = [rebuild(a) for a in arg_templates]
            new_kwargs = dict(static_kwargs)
            for k in arr_kwarg_names:
                new_kwargs[k] = Tensor._wrap(akw[k])
            # bind live layer state for the trace; restore after so no
            # tracer leaks into the eager world (e.g. BN running stats
            # mutated inside the traced forward)
            originals = [t._array for t in state_tensors]
            try:
                for t, a in zip(state_tensors, state_arrays):
                    t._array = a
                out = fn(*new_args, **new_kwargs)
            finally:
                for t, o in zip(state_tensors, originals):
                    t._array = o
            return jax.tree_util.tree_map(
                lambda t: t._array if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))

        return jax.jit(pure_fn)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """Decorator analog of paddle.jit.to_static (jit/api.py:to_static)."""

    def decorate(fn):
        if isinstance(fn, StaticFunction):
            return fn
        # layer: wrap its forward
        from paddle_tpu.nn.layer import Layer

        if isinstance(fn, Layer):
            fn.forward = StaticFunction(fn.forward, input_spec,
                                        build_strategy, backend, layer=fn)
            return fn
        return StaticFunction(fn, input_spec, build_strategy, backend)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def count_traces(fn):
    """Trace-count probe: wrap a python callable BEFORE handing it to
    jax.jit so every retrace (jit cache miss) increments `.traces` —
    jax re-invokes the python function exactly once per new
    (shape, dtype) signature. CI uses this to PROVE a steady-state
    compiled path stays compiled (e.g. the generation engine's decode
    step must trace once, not once per request), instead of inferring
    it from wall-clock noise."""

    @functools.wraps(fn)
    def counted(*args, **kwargs):
        counted.traces += 1
        return fn(*args, **kwargs)

    counted.traces = 0
    return counted


@contextmanager
def expect_traces(counted, n):
    """Assertion helper over a `count_traces` probe: the wrapped block
    must trigger EXACTLY n new traces (n=0 asserts no recompiles —
    the steady-state-decode CI contract)."""
    if not hasattr(counted, "traces"):
        raise TypeError("expect_traces needs a count_traces-wrapped "
                        "callable (missing .traces)")
    before = counted.traces
    yield
    got = counted.traces - before
    if got != n:
        raise AssertionError(
            f"expected {n} trace(s) of {getattr(counted, '__name__', counted)} "
            f"in this block, observed {got} — a compiled path is "
            "retracing (shape/dtype drift or python-object cache-key "
            "churn)")


def dedup_params(params):
    """Identity-dedup for parameter/buffer lists: a layer registered
    under two parents (shared submodules) must not produce a
    twice-donated array."""
    seen, out = set(), []
    for p in params:
        if id(p) not in seen:
            seen.add(id(p))
            out.append(p)
    return out


def model_buffers(model):
    """The ordered buffer list threaded through compiled steps (must be
    identical between make_forward_loss and the caller's writeback),
    identity-deduplicated."""
    return dedup_params(model.buffers() if hasattr(model, "buffers")
                        else [])


def make_forward_loss(model, loss_fn, params, with_outputs=False,
                      buffers=None):
    """The traced forward: bind param AND buffer arrays into the live
    Tensors, run the eager forward under the per-step rng, return
    (loss, (new_buffers, outputs-or-None)). Buffer mutations made by the
    forward (BN running stats, SpectralNorm power-iteration u/v) are
    captured before bound_state restores the eager arrays, so compiled
    steps persist them — the analog of the reference's in-place
    MomentumTensor updates inside run_program. Shared by build_step_fn
    and the gradient-accumulation programs."""
    from paddle_tpu.core import random as random_mod

    if buffers is None:
        buffers = model_buffers(model)

    def forward_loss(param_arrays, buf_arrays, inputs, label, rng):
        # rng is the per-step traced key that dropout & friends derive
        # from (random.key_scope)
        with bound_state(zip(params + buffers,
                             list(param_arrays) + list(buf_arrays)),
                         params + buffers):
            with random_mod.key_scope(rng):
                out = model(*inputs) if isinstance(inputs, tuple) else model(inputs)
                loss = loss_fn(out, Tensor._wrap(label)) if loss_fn is not None else out
            loss_arr = loss._array if isinstance(loss, Tensor) else loss
            # capture in-trace buffer writes BEFORE bound_state restores;
            # stop_gradient — buffer state is never a differentiable path
            new_bufs = [jax.lax.stop_gradient(b._array) for b in buffers]
            out_arrs = None
            if with_outputs:
                out_arrs = jax.tree_util.tree_map(
                    lambda t: t._array if isinstance(t, Tensor) else t, out,
                    is_leaf=lambda t: isinstance(t, Tensor))
            return loss_arr, (new_bufs, out_arrs)

    return forward_loss


def make_update_fn(opt, acc_idx, params):
    """The optimizer tail: clip + per-param single_update over merged
    accumulator slots. (param_arrays, grads, accums, lr, step) ->
    (new_params, new_accums). Shared by build_step_fn and the
    gradient-merge apply program."""
    opt._ensure_state()
    single_update = opt._single_update
    accum_names = list(opt._accumulators.keys())
    grad_clip = opt._grad_clip
    extras_list = [opt._per_param_extras(j) for j in acc_idx]
    # ASP n:m sparsity masks (incubate.asp.prune_model sets _asp_mask):
    # re-applied after every compiled update so sparsity holds on the
    # TrainStep paths too, not just eager optimizer.step (the reference's
    # OptimizerWithSparsityGuarantee runs inside minimize). Masks are
    # constants baked at trace time — prune before the first step.
    asp_masks = [getattr(p, "_asp_mask", None) for p in params]

    def update(param_arrays, grads, accums, lr, step, skip=None):
        if grad_clip is not None:
            # under pjit the norm reduction is mesh-global: XLA inserts
            # the cross-shard collectives
            # (hybrid_parallel_optimizer.py:186)
            grads = grad_clip._clip_arrays(list(grads))
        new_params, new_accums = [], {k: [] for k in accum_names}
        for i, (p, g) in enumerate(zip(param_arrays, grads)):
            acc_i = {k: accums[k][i] for k in accum_names}
            np_, na = single_update(p, g, acc_i, lr, step,
                                    extras=extras_list[i])
            if asp_masks[i] is not None:
                np_ = np_ * jnp.asarray(asp_masks[i], np_.dtype)
            if skip is not None:
                # skip the whole update on overflow (GradScaler.step
                # semantics): params and opt state keep their old values
                np_ = jnp.where(skip, p, np_)
                na = {k: jnp.where(skip, acc_i[k], v)
                      for k, v in na.items()}
            new_params.append(np_)
            for k in accum_names:
                new_accums[k].append(na.get(k, acc_i[k]))
        return new_params, new_accums

    return update


def build_step_fn(model, opt, loss_fn, params, acc_idx,
                  with_outputs=False, with_scaler=False, buffers=None):
    """The ONE compiled-train-step body shared by jit.TrainStep (single
    device) and distributed.DistributedTrainStep (SPMD — which adds
    shardings around it): value_and_grad over the model's eager forward
    with params bound as traced args, grad clip, then the optimizer's
    per-param update. Signature of the returned fn:
    (param_arrays, accums, bufs, lr, step, inputs, label, rng) ->
    (loss, new_params, new_accums, new_bufs) — or with_outputs=True:
    ((loss, out), ...), the hapi train-metrics path (outputs ride along
    as value_and_grad aux, no second forward). `bufs` are the model's
    non-trainable buffers (BN running stats, spectral-norm u/v) whose
    in-forward updates persist across compiled steps."""
    if buffers is None:
        buffers = model_buffers(model)
    forward_loss = make_forward_loss(model, loss_fn, params, with_outputs,
                                     buffers=buffers)
    update = make_update_fn(opt, acc_idx, params)

    def step_fn(param_arrays, accums, bufs, lr, step, inputs, label, rng,
                scale=None):
        if with_scaler:
            # the UNSCALED loss rides along as aux, so the reported loss
            # stays exact even when the scaled one overflows
            def scaled_loss(pa, ins, lb, r):
                loss, aux = forward_loss(pa, bufs, ins, lb, r)
                return loss * scale, (loss, aux)
            (_, (loss, (new_bufs, out))), grads = jax.value_and_grad(
                scaled_loss, has_aux=True)(
                param_arrays, inputs, label, rng)
            found_inf = jnp.logical_not(jnp.stack(
                [jnp.all(jnp.isfinite(g)) for g in grads]).all())
            # divide, don't multiply by 1/scale: at large scales the
            # reciprocal is subnormal and XLA flushes it to zero
            grads = [(g.astype(jnp.float32) / scale).astype(p.dtype)
                     for g, p in zip(grads, param_arrays)]
            # a skipped step must not advance buffer state either
            new_bufs = [jnp.where(found_inf, b, nb)
                        for b, nb in zip(bufs, new_bufs)]
        else:
            (loss, (new_bufs, out)), grads = jax.value_and_grad(
                forward_loss, has_aux=True)(
                param_arrays, bufs, inputs, label, rng)
        from paddle_tpu.framework import nan_inf

        if nan_inf.check_enabled():
            # FLAGS_check_nan_inf inside the compiled step: loss + every
            # grad, named, via one staged host callback (SURVEY §7)
            named = [("loss", loss)] + [
                (f"{getattr(p, 'name', None) or f'param{i}'}.grad", g)
                for i, (p, g) in enumerate(zip(params, grads))]
            nan_inf.stage_check(named, "compiled train step")
        new_params, new_accums = update(
            param_arrays, grads, accums, lr, step,
            skip=found_inf if with_scaler else None)
        if with_outputs:
            loss = (loss, out)
        if with_scaler:
            return loss, found_inf, new_params, new_accums, new_bufs
        return loss, new_params, new_accums, new_bufs

    return step_fn


def make_accum_fns(model, optimizer, loss_fn, params, acc_idx, K,
                   avg=True, with_scaler=False):
    """Gradient-merge closure pair shared by TrainStep and
    DistributedTrainStep: accumulate (forward+backward into f32
    buffers, no update; FLAGS_check_nan_inf staged per micro-step) and
    apply (optimizer update from the MEAN — or SUM when avg=False,
    GradientMergeOptimizer parity — buffers zeroed). Built from the
    same make_forward_loss/make_update_fn pieces as the normal step so
    clip/nan-check behavior can't drift; callers add their own jit
    options/shardings.

    with_scaler=True (GradScaler x gradient accumulation, the
    reference's gradient_merge + amp composition): acc_fn gains
    (found, ..., scale) and accumulates SCALED f32 grads while OR-ing
    per-micro-step non-finiteness into `found`; upd_fn divides by
    scale*K and skips the whole window's update on overflow, exactly
    like the unaccumulated GradScaler.step path."""
    from paddle_tpu.framework import nan_inf

    buffers = model_buffers(model)
    forward_loss = make_forward_loss(model, loss_fn, params,
                                     buffers=buffers)
    update = make_update_fn(optimizer, acc_idx, params)

    def _grads_and_bufs(param_arrays, model_bufs, inputs, label, rng,
                        scale):
        if with_scaler:
            def scaled_loss(pa, ins, lb, r):
                loss, aux = forward_loss(pa, model_bufs, ins, lb, r)
                return loss * scale, (loss, aux)
            (_, (loss, (new_model_bufs, _))), grads = jax.value_and_grad(
                scaled_loss, has_aux=True)(param_arrays, inputs, label,
                                           rng)
        else:
            (loss, (new_model_bufs, _)), grads = jax.value_and_grad(
                forward_loss, has_aux=True)(
                param_arrays, model_bufs, inputs, label, rng)
        if nan_inf.check_enabled():
            named = [("loss", loss)] + [
                (f"{getattr(p, 'name', None) or f'param{i}'}.grad", g)
                for i, (p, g) in enumerate(zip(params, grads))]
            nan_inf.stage_check(named, "gradient-merge micro-step")
        return loss, grads, new_model_bufs

    if with_scaler:
        def acc_fn(bufs, found, param_arrays, model_bufs, inputs, label,
                   rng, scale):
            loss, grads, new_model_bufs = _grads_and_bufs(
                param_arrays, model_bufs, inputs, label, rng, scale)
            micro_inf = jnp.logical_not(jnp.stack(
                [jnp.all(jnp.isfinite(g)) for g in grads]).all())
            # an overflowed micro-step must not advance buffer state
            # (matches the unaccumulated scaler step)
            new_model_bufs = [jnp.where(micro_inf, b, nb)
                              for b, nb in zip(model_bufs,
                                               new_model_bufs)]
            return (loss, [b + g.astype(jnp.float32)
                           for b, g in zip(bufs, grads)],
                    jnp.logical_or(found, micro_inf), new_model_bufs)

        def upd_fn(param_arrays, accums, bufs, lr, step, scale, found):
            div = (K if avg else 1)
            # divide by the (large) scale BEFORE the micro-count: the
            # scaled f32 sum stays far from overflow, and dividing by
            # scale avoids the subnormal-reciprocal trap
            grads = [(b / scale / div).astype(p.dtype)
                     for b, p in zip(bufs, param_arrays)]
            new_params, new_accums = update(param_arrays, grads, accums,
                                            lr, step, skip=found)
            zeroed = [jnp.zeros_like(b) for b in bufs]
            return new_params, new_accums, zeroed

        return acc_fn, upd_fn

    def acc_fn(bufs, param_arrays, model_bufs, inputs, label, rng):
        loss, grads, new_model_bufs = _grads_and_bufs(
            param_arrays, model_bufs, inputs, label, rng, None)
        return loss, [b + g.astype(jnp.float32)
                      for b, g in zip(bufs, grads)], new_model_bufs

    def upd_fn(param_arrays, accums, bufs, lr, step):
        div = K if avg else 1
        grads = [(b / div).astype(p.dtype)
                 for b, p in zip(bufs, param_arrays)]
        new_params, new_accums = update(param_arrays, grads, accums,
                                        lr, step)
        zeroed = [jnp.zeros_like(b) for b in bufs]
        return new_params, new_accums, zeroed

    return acc_fn, upd_fn


def gather_accums(opt, acc_idx):
    """Select the accumulator slots for the trained-param subset (aligned
    with acc_idx into the optimizer's parameter list)."""
    return {k: [v[j] for j in acc_idx] for k, v in opt._accumulators.items()}


def scatter_accums(opt, acc_idx, new_accums):
    """Write updated accumulator slots back to their optimizer positions."""
    for k in opt._accumulators:
        for out_pos, j in enumerate(acc_idx):
            opt._accumulators[k][j] = new_accums[k][out_pos]


class TrainStep:
    """One fully-compiled training step over (model, optimizer, loss_fn).

    Usage:
        step = TrainStep(model, opt, loss_fn)   # loss_fn(model_out, label)
        loss = step(x, label)                   # one XLA execution

    Functionalizes parameters + optimizer state into pytrees, runs
    jax.value_and_grad over the forward, applies the optimizer update, and
    donates old params/opt-state buffers (in-place update in HBM). This is
    the idiomatic-TPU replacement for the reference's to_static training
    (run_program_op + InterpreterCore) and is what bench.py measures.
    """

    def __init__(self, model, optimizer, loss_fn=None, donate=True,
                 with_outputs=False, accumulate_steps=1, scaler=None,
                 telemetry=None):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.with_outputs = with_outputs
        # observability.TrainingTelemetry: when attached, each __call__
        # is timed end-to-end (blocking on the loss so the histogram
        # sees device time, not async dispatch) and recorded as one
        # step observation — the only sync telemetry costs
        self.telemetry = telemetry
        # gradient merge (GradientMergeOptimizer k_steps analog): grads
        # from K successive micro-batch calls accumulate in device
        # buffers; the optimizer applies the MEAN on the K-th call
        self.accumulate_steps = int(accumulate_steps)
        self._accum_count = 0
        self._grad_bufs = None
        # fp16 loss scaling (GradScaler) INSIDE the compiled step: scale
        # loss, unscale grads, skip the update when any grad is non-finite
        self.scaler = scaler
        if with_outputs and self.accumulate_steps > 1:
            raise NotImplementedError(
                "accumulate_steps with with_outputs is not supported")
        optimizer._ensure_state()
        # The traced/updated set is the intersection of the model's
        # trainable params (stop_gradient=False — frozen params stay baked
        # as constants, matching eager Optimizer.step skipping grad-None
        # params) and the optimizer's parameter list (whose accumulator
        # slots we must index consistently).
        opt_index = {id(p): j for j, p in enumerate(optimizer._parameter_list)}
        self._params = dedup_params(
            p for p in model.parameters()
            if not p.stop_gradient and id(p) in opt_index)
        self._acc_idx = [opt_index[id(p)] for p in self._params]
        # buffers thread through the compiled step so in-forward updates
        # (BN running stats, spectral-norm u/v) persist across steps
        self._buffers = model_buffers(model)
        self._jitted = None
        self._scan_jitted = None
        self._donate = donate
        self._opt_state = None

    def _build(self):
        # donation layout published via jit.introspect so tooling
        # (tpu-lint) reads it instead of string-matching this file
        return jax.jit(self._make_step_fn(),
                       donate_argnums=introspect.TRAINSTEP_DONATE_ARGNUMS
                       if self._donate else ())

    def _buf_arrays(self):
        return [b._array for b in self._buffers]

    def _write_buffers(self, new_bufs):
        for b, a in zip(self._buffers, new_bufs):
            b._array = a

    def _gather_accums(self):
        return gather_accums(self.optimizer, self._acc_idx)

    def _scatter_accums(self, new_accums):
        scatter_accums(self.optimizer, self._acc_idx, new_accums)

    def _next_step_key(self):
        from paddle_tpu.core import random as random_mod

        return random_mod.next_key()

    def _with_scaler(self):
        return self.scaler is not None and self.scaler.is_enable()

    def _check_plain(self, what):
        """Multi-step scan paths support neither loss scaling nor
        gradient merge (the scan body applies a full update per step)."""
        if self._with_scaler():
            raise NotImplementedError(
                f"{what} does not support a GradScaler; call the step "
                "per batch instead")
        if self.accumulate_steps > 1:
            raise NotImplementedError(
                f"{what} does not support accumulate_steps>1; call the "
                "step per micro-batch instead")

    def _make_step_fn(self):
        return build_step_fn(self.model, self.optimizer, self.loss_fn,
                             self._params, self._acc_idx,
                             with_outputs=self.with_outputs,
                             with_scaler=self._with_scaler(),
                             buffers=self._buffers)

    def run_scan(self, inputs_stacked, labels_stacked):
        """Run a whole sequence of steps inside ONE XLA program via
        lax.scan — amortizes dispatch latency to zero and lets XLA overlap
        steps. inputs/labels have a leading [num_steps] dim. Returns the
        per-step losses. (The analog of the reference's
        Executor.train_from_dataset inner loop, compiled.)"""
        from paddle_tpu.framework.flags import debug_epoch

        if self._scan_jitted is None or \
                getattr(self, "_scan_epoch", None) != debug_epoch():
            self.optimizer._ensure_state()
            self._scan_jitted = self._build_scan()
            self._scan_epoch = debug_epoch()
        xs = _unwrap(inputs_stacked)
        ys = _unwrap(labels_stacked)
        return self._dispatch_steps(
            lambda pa, acc, bufs, lr, st, rng: self._scan_jitted(
                pa, acc, bufs, lr, st, xs, ys, rng),
            int(xs.shape[0]))

    def run_repeat(self, inputs, labels, steps):
        """Like run_scan but re-feeds ONE batch for `steps` steps inside
        a single XLA program — throughput benchmarking without holding
        `steps` copies of the data in HBM (a [steps, batch, ...] stack of
        224px images overflows a chip long before compute does)."""
        assert not self.with_outputs, \
            "run_repeat returns losses only; use with_outputs=False"
        self._check_plain("run_repeat")
        from paddle_tpu.framework.flags import debug_epoch

        xs = _unwrap(inputs)
        ys = _unwrap(labels)
        key = ("repeat", xs.shape, str(xs.dtype), debug_epoch())
        if getattr(self, "_repeat_key", None) != key:
            self.optimizer._ensure_state()
            base_step = self._make_step_fn()

            def repeat_all(param_arrays, accums, bufs, lr, step0, x, y, n,
                           rng):
                def body(carry, i):
                    params, accs, mb, st = carry
                    loss, nparams, naccs, nmb = base_step(
                        params, accs, mb, lr, st, (x,), y,
                        jax.random.fold_in(rng, st))
                    return (nparams, naccs, nmb, st + 1), loss

                (fp, fa, fb, _), losses = jax.lax.scan(
                    body, (param_arrays, accums, bufs, step0),
                    jnp.arange(n, dtype=jnp.int32))
                return losses, fp, fa, fb

            self._repeat_jitted = jax.jit(
                repeat_all, static_argnames="n",
                donate_argnums=introspect.TRAINSTEP_DONATE_ARGNUMS
                if self._donate else ())
            self._repeat_key = key
        losses = self._dispatch_steps(
            lambda pa, acc, bufs, lr, st, rng: self._repeat_jitted(
                pa, acc, bufs, lr, st, xs, ys, steps, rng),
            steps)
        return losses

    def _build_accum_fns(self):
        """Two programs for gradient merge (shared closures from
        make_accum_fns so the mesh edition can't drift)."""
        acc_fn, upd_fn = make_accum_fns(
            self.model, self.optimizer, self.loss_fn, self._params,
            self._acc_idx, self.accumulate_steps,
            with_scaler=self._with_scaler())
        donate = introspect.ACCUM_DONATE_ARGNUMS if self._donate else ()
        return (jax.jit(acc_fn, donate_argnums=donate),
                jax.jit(upd_fn,
                        donate_argnums=introspect.TRAINSTEP_DONATE_ARGNUMS
                        if self._donate else ()))

    def _call_accumulate(self, in_arrays, label_arr):
        from paddle_tpu.framework.flags import debug_epoch

        opt = self.optimizer
        key = (debug_epoch(), self._with_scaler())
        if getattr(self, "_acc_jitted", None) is None or \
                getattr(self, "_acc_epoch", None) != key:
            self._acc_jitted, self._upd_jitted = self._build_accum_fns()
            self._acc_epoch = key
        if self._grad_bufs is None:
            self._grad_bufs = [jnp.zeros(p._array.shape, jnp.float32)
                               for p in self._params]
        with_scaler = self._with_scaler()
        if with_scaler:
            scale = jnp.float32(self.scaler.get_scale())
            found = getattr(self, "_accum_found", None)
            if found is None:
                found = jnp.bool_(False)
            loss, self._grad_bufs, found, new_model_bufs = \
                self._acc_jitted(
                    self._grad_bufs, found,
                    [p._array for p in self._params],
                    self._buf_arrays(), in_arrays, label_arr,
                    self._next_step_key(), scale)
            self._accum_found = found
        else:
            loss, self._grad_bufs, new_model_bufs = self._acc_jitted(
                self._grad_bufs, [p._array for p in self._params],
                self._buf_arrays(), in_arrays, label_arr,
                self._next_step_key())
        self._write_buffers(new_model_bufs)
        self._accum_count += 1
        if self._accum_count >= self.accumulate_steps:
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            stepc = jnp.asarray(opt._step_count, jnp.int32)
            if with_scaler:
                new_params, new_accums, self._grad_bufs = \
                    self._upd_jitted(
                        [p._array for p in self._params],
                        self._gather_accums(), self._grad_bufs, lr,
                        stepc, scale, self._accum_found)
                skipped = bool(self._accum_found)
                self.scaler._found_inf = skipped
                self.scaler.update()
                self._accum_found = jnp.bool_(False)
            else:
                new_params, new_accums, self._grad_bufs = \
                    self._upd_jitted(
                        [p._array for p in self._params],
                        self._gather_accums(), self._grad_bufs, lr,
                        stepc)
                skipped = False
            for p, a in zip(self._params, new_params):
                p._in_place_update(a)
            self._scatter_accums(new_accums)
            if not skipped:
                opt._step_count += 1
            self._accum_count = 0
        return Tensor._wrap(loss)

    def _dispatch_steps(self, call, nsteps):
        """Shared multi-step dispatch + writeback tail (run_scan and
        run_repeat): gather live state, run, write params/accums back,
        advance the step counter."""
        opt = self.optimizer
        param_arrays = [p._array for p in self._params]
        accums = self._gather_accums()
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        stepc = jnp.asarray(opt._step_count, jnp.int32)
        losses, new_params, new_accums, new_bufs = call(
            param_arrays, accums, self._buf_arrays(), lr, stepc,
            self._next_step_key())
        for p, a in zip(self._params, new_params):
            p._in_place_update(a)
        self._scatter_accums(new_accums)
        self._write_buffers(new_bufs)
        opt._step_count += nsteps
        return Tensor._wrap(losses)

    def _build_scan(self):
        assert not self.with_outputs, \
            "run_scan returns losses only; use with_outputs=False"
        self._check_plain("run_scan")
        base_step = self._make_step_fn()

        def scan_all(param_arrays, accums, bufs, lr, step0, xs, ys, rng):
            def body(carry, xy):
                params, accs, mb, st = carry
                x, y = xy
                loss, nparams, naccs, nmb = base_step(
                    params, accs, mb, lr, st, (x,), y,
                    jax.random.fold_in(rng, st))
                return (nparams, naccs, nmb, st + 1), loss

            (fparams, faccums, fbufs, _), losses = jax.lax.scan(
                body, (param_arrays, accums, bufs, step0), (xs, ys))
            return losses, fparams, faccums, fbufs

        donate = introspect.TRAINSTEP_DONATE_ARGNUMS if self._donate \
            else ()
        return jax.jit(scan_all, donate_argnums=donate)

    def __call__(self, *inputs, label=None):
        if self.telemetry is None:
            return self._call_inner(*inputs, label=label)
        import time

        # gradient merge: the K micro-batch calls of one optimizer step
        # record ONE observation, timed cycle-start to K-th-call-loss
        # with a single block — mid-cycle calls stay async so telemetry
        # doesn't serialize the dispatch pipeline
        if getattr(self, "_tel_t0", None) is None:
            self._tel_t0 = time.perf_counter()
        try:
            out = self._call_inner(*inputs, label=label)
        except BaseException:
            # a failed micro-batch must not leave the cycle timer armed
            # — the next successful cycle would observe failure + idle
            # time as one giant step. If earlier micro-batches of this
            # cycle already ran, the cycle completes with a PARTIAL
            # re-armed timer: taint it so no skewed observation lands.
            self._tel_t0 = None
            if self.accumulate_steps > 1 and self._accum_count != 0:
                self._tel_taint = True
            raise
        if self.accumulate_steps > 1 and self._accum_count != 0:
            return out                     # mid-cycle micro-batch
        if getattr(self, "_tel_taint", False):
            self._tel_taint = False        # tainted cycle: no sample
            self._tel_t0 = None
            return out
        loss_t = out[0] if isinstance(out, tuple) else out
        jax.block_until_ready(loss_t._array)
        dt = time.perf_counter() - self._tel_t0
        self._tel_t0 = None
        loss_val = float(loss_t._array) \
            if getattr(loss_t._array, "size", 0) == 1 else None
        self.telemetry.observe_step(dt, loss=loss_val)
        return out

    def _call_inner(self, *inputs, label=None):
        if label is None and len(inputs) >= 2:
            *inputs, label = inputs
            inputs = tuple(inputs)
        from paddle_tpu.framework.flags import debug_epoch

        build_key = (debug_epoch(), self._with_scaler())
        if self._jitted is None or \
                getattr(self, "_build_key", None) != build_key:
            self.optimizer._ensure_state()
            self._jitted = self._build()
            self._scan_jitted = None
            self._build_key = build_key
        opt = self.optimizer
        in_arrays = tuple(_unwrap(i) for i in inputs)
        label_arr = _unwrap(label) if label is not None else None
        if self.accumulate_steps > 1:
            return self._call_accumulate(in_arrays, label_arr)
        param_arrays = [p._array for p in self._params]
        accums = self._gather_accums()
        bufs = self._buf_arrays()
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        stepc = jnp.asarray(opt._step_count, jnp.int32)
        if self._with_scaler():
            loss, found_inf, new_params, new_accums, new_bufs = \
                self._jitted(
                    param_arrays, accums, bufs, lr, stepc, in_arrays,
                    label_arr, self._next_step_key(),
                    jnp.float32(self.scaler.get_scale()))
            skipped = bool(found_inf)
            self.scaler._found_inf = skipped
            self.scaler.update()
        else:
            loss, new_params, new_accums, new_bufs = self._jitted(
                param_arrays, accums, bufs, lr, stepc, in_arrays,
                label_arr, self._next_step_key())
            skipped = False
        for p, a in zip(self._params, new_params):
            p._in_place_update(a)
        self._scatter_accums(new_accums)
        self._write_buffers(new_bufs)
        if not skipped:
            # a scaler-skipped step doesn't count (GradScaler.step skips
            # optimizer.step entirely — bias-correction t must match the
            # number of REAL updates the moments saw)
            opt._step_count += 1
        if self.with_outputs:
            loss, out = loss
            return Tensor._wrap(loss), jax.tree_util.tree_map(Tensor._wrap, out)
        return Tensor._wrap(loss)
