from .api import (InputSpec, TrainStep, count_traces, expect_traces,
                  not_to_static, to_static)
from .save_load import TranslatedLayer, load, save

__all__ = ["to_static", "not_to_static", "TrainStep", "InputSpec", "save",
           "load", "TranslatedLayer", "count_traces", "expect_traces"]
