from .api import InputSpec, TrainStep, not_to_static, to_static
from .save_load import TranslatedLayer, load, save

__all__ = ["to_static", "not_to_static", "TrainStep", "InputSpec", "save",
           "load", "TranslatedLayer"]
