from .api import TrainStep, not_to_static, to_static
from .save_load import load, save

__all__ = ["to_static", "not_to_static", "TrainStep", "save", "load"]
