"""BERT / ERNIE — the encoder model family for the BASELINE.md row-2
benchmark (ERNIE-3.0-base / BERT-base fine-tune, tokens/sec/chip).

The reference trains these through PaddleNLP on top of the framework
(tools/ci_model_benchmark.sh clones PaddleNLP and times BERT); the
architecture here is the canonical post-LN transformer encoder. ERNIE
1.0/3.0-base share the BERT compute graph (different vocab/pretraining
objectives), so `ErnieModel` is a configured `BertModel`.

TPU-native choices: fused QKV (one MXU matmul), flash attention via
F.scaled_dot_product_attention (bidirectional — pallas kernel, no mask
materialization), bf16-friendly LayerNorms, static shapes throughout.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.ops import manipulation as mp


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_epsilon: float = 1e-12
    initializer_range: float = 0.02
    num_labels: int = 2

    @staticmethod
    def bert_base():
        return BertConfig()

    @staticmethod
    def bert_large():
        return BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                          intermediate_size=4096)

    @staticmethod
    def ernie_base():
        # ERNIE-1.0 base: same encoder, 18000-token Chinese vocab
        return BertConfig(vocab_size=18000)

    @staticmethod
    def ernie_3_base():
        # ERNIE-3.0-base-zh: L12 H768 A12, 40000 vocab, seq 512
        return BertConfig(vocab_size=40000)

    @staticmethod
    def tiny(vocab=128, hidden=64, layers=2, heads=4, seq=64):
        return BertConfig(vocab_size=vocab, hidden_size=hidden,
                          num_layers=layers, num_heads=heads,
                          intermediate_size=4 * hidden,
                          max_position_embeddings=seq)


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        init = nn.initializer.Normal(0.0, config.initializer_range)
        attr = nn.ParamAttr(initializer=init)
        self.word_embeddings = nn.Embedding(
            config.vocab_size, config.hidden_size, weight_attr=attr)
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=attr)
        self.token_type_embeddings = nn.Embedding(
            config.type_vocab_size, config.hidden_size, weight_attr=attr)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_epsilon)
        self.dropout = nn.Dropout(config.hidden_dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        S = input_ids.shape[1]
        if position_ids is None:
            position_ids = paddle.arange(S, dtype="int32")
        h = self.word_embeddings(input_ids) \
            + self.position_embeddings(position_ids)
        if token_type_ids is None:
            # BERT convention: omitted token_type_ids means type 0 — the
            # type-0 row still participates (and trains)
            h = h + self.token_type_embeddings.weight[0]
        else:
            h = h + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(h))


class BertSelfAttention(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        init = nn.initializer.Normal(0.0, config.initializer_range)
        attr = nn.ParamAttr(initializer=init)
        self.qkv_proj = nn.Linear(config.hidden_size, 3 * config.hidden_size,
                                  weight_attr=attr)
        self.out_proj = nn.Linear(config.hidden_size, config.hidden_size,
                                  weight_attr=attr)
        self.dropout_p = config.attention_dropout

    def forward(self, x, attn_mask=None):
        B, S, H = x.shape
        qkv = self.qkv_proj(x)
        qkv = mp.reshape(qkv, [B, S, 3, self.num_heads, self.head_dim])
        q, k, v = mp.unbind(qkv, axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=False,
            dropout_p=self.dropout_p, training=self.training)
        return self.out_proj(mp.reshape(out, [B, S, H]))


class BertLayer(nn.Layer):
    """Post-LN encoder block (the original BERT residual order)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        init = nn.initializer.Normal(0.0, config.initializer_range)
        attr = nn.ParamAttr(initializer=init)
        self.attention = BertSelfAttention(config)
        self.ln1 = nn.LayerNorm(config.hidden_size,
                                epsilon=config.layer_norm_epsilon)
        self.fc1 = nn.Linear(config.hidden_size, config.intermediate_size,
                             weight_attr=attr)
        self.fc2 = nn.Linear(config.intermediate_size, config.hidden_size,
                             weight_attr=attr)
        self.ln2 = nn.LayerNorm(config.hidden_size,
                                epsilon=config.layer_norm_epsilon)
        self.dropout = nn.Dropout(config.hidden_dropout)

    def forward(self, x, attn_mask=None):
        x = self.ln1(x + self.dropout(self.attention(x, attn_mask)))
        h = self.fc2(F.gelu(self.fc1(x), approximate=True))
        return self.ln2(x + self.dropout(h))


class BertPooler(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.dense = nn.Linear(config.hidden_size, config.hidden_size,
                               weight_attr=nn.ParamAttr(initializer=init))

    def forward(self, hidden):  # [B,S,H] -> [B,H] from the [CLS] position
        return F.tanh(self.dense(hidden[:, 0]))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig, with_pooler: bool = True):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.layers = nn.LayerList([BertLayer(config)
                                    for _ in range(config.num_layers)])
        self.pooler = BertPooler(config) if with_pooler else None

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                position_ids=None):
        """attention_mask: [B,S] with 1 for real tokens, 0 for padding
        (paddle/HF convention); converted to an additive logit mask."""
        attn_mask = None
        if attention_mask is not None:
            # [B,S] -> additive [B,1,1,S]
            m = (1.0 - attention_mask.astype("float32")) * -1e30
            attn_mask = m.reshape([m.shape[0], 1, 1, m.shape[1]])
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        for layer in self.layers:
            h = layer(h, attn_mask)
        if self.pooler is not None:
            return h, self.pooler(h)
        return h

    def num_params(self):
        return sum(p.size for p in self.parameters())


class ErnieModel(BertModel):
    """ERNIE shares the BERT encoder graph; pretraining differences
    (knowledge masking, task embeddings) live in the data pipeline."""


class BertForSequenceClassification(nn.Layer):
    """The fine-tune benchmark head (BASELINE.md row 2)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout)
        self.classifier = nn.Linear(config.hidden_size, config.num_labels)
        self.config = config

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels)
        return logits

    def loss_fn(self, logits, labels):
        return F.cross_entropy(logits, labels)

    def num_params(self):
        return sum(p.size for p in self.parameters())

    def flops_per_token(self, seq_len):
        """Training FLOPs/token: 6N over MATMUL params only (embedding
        tables are gathers, and unlike GPT there is no tied vocab
        projection to re-use them as a matmul), plus the attention
        score/value matmuls (12*L*H*S, bidirectional)."""
        c = self.config
        emb = self.bert.embeddings
        n_embed = sum(p.size for p in emb.word_embeddings.parameters()) \
            + sum(p.size for p in emb.position_embeddings.parameters()) \
            + sum(p.size for p in emb.token_type_embeddings.parameters())
        n_matmul = self.num_params() - n_embed
        return 6 * n_matmul + 12 * c.num_layers * c.hidden_size * seq_len


class BertPretrainingHeads(nn.Layer):
    """MLM head (tied decoder) + NSP head."""

    def __init__(self, config: BertConfig, embedding_weight):
        super().__init__()
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.transform = nn.Linear(config.hidden_size, config.hidden_size,
                                   weight_attr=nn.ParamAttr(initializer=init))
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_epsilon)
        self.decoder_weight = embedding_weight  # tied [V,H]
        self.decoder_bias = self.create_parameter(
            [config.vocab_size], is_bias=True,
            default_initializer=nn.initializer.Constant(0.0))
        self.seq_relationship = nn.Linear(config.hidden_size, 2)

    def forward(self, hidden, pooled):
        h = self.layer_norm(F.gelu(self.transform(hidden), approximate=True))
        mlm_logits = paddle.matmul(h, self.decoder_weight, transpose_y=True) \
            + self.decoder_bias
        nsp_logits = self.seq_relationship(pooled)
        return mlm_logits, nsp_logits


class BertForPretraining(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.heads = BertPretrainingHeads(
            config, self.bert.embeddings.word_embeddings.weight)
        self.config = config

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                mlm_labels=None, nsp_labels=None):
        hidden, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        mlm_logits, nsp_logits = self.heads(hidden, pooled)
        if mlm_labels is None:
            return mlm_logits, nsp_logits
        loss = F.cross_entropy(
            mp.reshape(mlm_logits, [-1, self.config.vocab_size]),
            mp.reshape(mlm_labels, [-1]), ignore_index=-100)
        if nsp_labels is not None:
            loss = loss + F.cross_entropy(nsp_logits, nsp_labels)
        return loss
