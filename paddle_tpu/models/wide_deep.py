"""Wide&Deep CTR model over PS-lite sparse tables.

The BASELINE wide&deep/DeepFM row trains sparse-feature CTR models
through the reference parameter server (models in PaddleRec, runtime
the_one_ps.py). This is the equivalent functional config: sparse id
features -> DistributedEmbedding (host-RAM table, pull/push), a wide
linear part over the same ids (dim-1 table) and a deep MLP over the
concatenated embeddings, sigmoid CTR head.
"""
from __future__ import annotations

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.ps import DistributedEmbedding, SparseAdagradRule

__all__ = ["WideDeep"]


class WideDeep(nn.Layer):
    """ids [B, num_fields] int64 -> click probability [B, 1].

    Dense (MLP) params train with a normal device optimizer; sparse
    rows train through the tables' accessor rules via push_sparse().
    """

    def __init__(self, num_fields, embedding_dim=8, hidden=(64, 32),
                 sparse_lr=0.05, nshards=None, deep_table=None,
                 wide_table=None):
        super().__init__()
        # explicit tables (e.g. ps.TableClient handles against the
        # service tier) win over the default in-trainer host-RAM tables
        self.embedding = DistributedEmbedding(
            0, embedding_dim, table=deep_table,
            rule=SparseAdagradRule(sparse_lr),
            nshards=nshards, name="deep_table")
        self.wide = DistributedEmbedding(
            0, 1, table=wide_table, rule=SparseAdagradRule(sparse_lr),
            nshards=nshards, name="wide_table")
        layers, d = [], num_fields * embedding_dim
        for h in hidden:
            layers += [nn.Linear(d, h), nn.ReLU()]
            d = h
        layers.append(nn.Linear(d, 1))
        self.deep = nn.Sequential(*layers)

    def forward(self, ids):
        B, nf = ids.shape
        emb = self.embedding(ids)                    # [B, nf, D]
        deep = self.deep(emb.reshape([B, -1]))       # [B, 1]
        wide = self.wide(ids).sum(axis=1)            # [B, 1]
        return F.sigmoid(deep + wide)

    def push_sparse(self):
        """After loss.backward(): apply sparse-row updates."""
        self.embedding.push_gradients()
        self.wide.push_gradients()
