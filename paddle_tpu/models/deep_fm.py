"""DeepFM CTR model over PS-lite sparse tables (BASELINE row 5's
"wide&deep/DeepFM" wording — the reference ships both through PaddleRec
on the parameter server, the_one_ps.py runtime).

Same DistributedEmbedding host-RAM tables as WideDeep
(models/wide_deep.py); the difference is the FM second-order term
computed from the SAME shared embeddings the deep MLP consumes:
0.5 * ((sum_f v_f)^2 - sum_f v_f^2) summed over the embedding dim —
the O(B*nf*D) identity for pairwise interactions.
"""
from __future__ import annotations

import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed.ps import DistributedEmbedding, SparseAdagradRule

__all__ = ["DeepFM"]


class DeepFM(nn.Layer):
    """ids [B, num_fields] int64 -> click probability [B, 1].

    first-order: dim-1 table (like WideDeep's wide part);
    second-order: FM pairwise interactions over the shared embeddings;
    deep: MLP over the concatenated embeddings. Dense params train on
    device; sparse rows via the tables' accessor rules (push_sparse).
    """

    def __init__(self, num_fields, embedding_dim=8, hidden=(64, 32),
                 sparse_lr=0.05, nshards=None, deep_table=None,
                 wide_table=None):
        super().__init__()
        self.embedding = DistributedEmbedding(
            0, embedding_dim, table=deep_table,
            rule=SparseAdagradRule(sparse_lr),
            nshards=nshards, name="fm_embedding")
        self.first_order = DistributedEmbedding(
            0, 1, table=wide_table, rule=SparseAdagradRule(sparse_lr),
            nshards=nshards, name="fm_first_order")
        layers, d = [], num_fields * embedding_dim
        for h in hidden:
            layers += [nn.Linear(d, h), nn.ReLU()]
            d = h
        layers.append(nn.Linear(d, 1))
        self.deep = nn.Sequential(*layers)

    def forward(self, ids):
        B, nf = ids.shape
        emb = self.embedding(ids)                       # [B, nf, D]
        first = self.first_order(ids).sum(axis=1)       # [B, 1]
        sum_sq = emb.sum(axis=1) ** 2                   # [B, D]
        sq_sum = (emb ** 2).sum(axis=1)                 # [B, D]
        fm = (0.5 * (sum_sq - sq_sum)).sum(axis=1, keepdim=True)
        deep = self.deep(emb.reshape([B, -1]))          # [B, 1]
        return F.sigmoid(first + fm + deep)

    def push_sparse(self):
        """After loss.backward(): apply sparse-row updates."""
        self.embedding.push_gradients()
        self.first_order.push_gradients()
