"""GPT — the flagship decoder-only LM (the BASELINE.md GPT-1.3B hybrid-
parallel config; analog of the PaddleNLP GPT the reference's fleet tests
train, e.g. hybrid_parallel_pp_transformer.py's tiny transformer).

TPU-native design choices:
- pre-norm residual blocks, bf16-friendly layer norms (fp32 stats);
- fused QKV projection (one MXU matmul instead of three);
- causal attention via ops.scaled_dot_product_attention, which routes to
  the Pallas flash kernel for long sequences;
- weights created through tensor-parallel-aware layers from
  distributed.mp_layers when a model-parallel degree > 1 is configured —
  under SPMD these annotate shardings instead of splitting buffers.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.jit import introspect
from paddle_tpu.ops import manipulation as mp


def _mp_degree():
    from paddle_tpu.distributed.topology import get_hybrid_communicate_group

    try:
        return get_hybrid_communicate_group().axis_size("mp")
    except Exception:
        return 1


# Collective budget of ONE tensor-parallel serving step of this model.
# The numbers live in `jit.introspect.GPT_SERVING_AXIS_BUDGET` — ONE
# per-(mesh axis, kind) table carrying counts AND payload-byte bounds,
# consumed by tpu-verify TPU104 (counts) and tpu-shard TPU301/304/305
# (axes + bytes) — and this module keeps the canonical alias because
# the helpers right below (_mp_all_gather / _vocab_parallel_embed) are
# the only places serving collectives come from. The engine's step
# contracts reference it lazily as
# "paddle_tpu.models.gpt:GPT_SERVING_COLLECTIVES".
GPT_SERVING_COLLECTIVES = introspect.GPT_SERVING_AXIS_BUDGET


def _mp_all_gather(t, mp_axis):
    """Concatenate a column-parallel activation's shards along the LAST
    axis inside a shard_map body (tiled all-gather; mesh axis-index
    order IS the engine's head/column order, so the concat reassembles
    the logical layout exactly). Gathering is pure data movement — the
    result is bit-identical to the unsharded activation, which is what
    keeps tensor-parallel serving token-exact vs mp=1."""
    import jax

    from paddle_tpu.ops.dispatch import apply

    def fn(a):
        return jax.lax.all_gather(a, mp_axis, axis=a.ndim - 1,
                                  tiled=True)

    return apply("mp_all_gather", fn, t)


def _vocab_parallel_embed(weight, token_ids, mp_axis):
    """Embedding lookup over a vocab-sharded table inside a shard_map
    body (VocabParallelEmbedding, inference edition): each shard
    gathers the rows it owns (out-of-range ids masked to zero rows),
    one psum assembles the full embedding. Every id hits exactly ONE
    shard, so the psum adds exact zeros — bit-identical to the
    unsharded gather."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.dispatch import apply

    def fn(w, ids):
        r = jax.lax.axis_index(mp_axis)
        vl = w.shape[0]
        loc = ids.astype(jnp.int32) - r * vl
        inb = (loc >= 0) & (loc < vl)
        rows = w[jnp.clip(loc, 0, vl - 1)]
        rows = jnp.where(inb[..., None], rows, jnp.zeros((), w.dtype))
        return jax.lax.psum(rows, mp_axis)

    return apply("vocab_parallel_embed", fn, weight, token_ids)


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    intermediate_size: int = None
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    use_bias: bool = True

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @staticmethod
    def gpt_small():
        return GPTConfig(hidden_size=768, num_layers=12, num_heads=12)

    @staticmethod
    def gpt_medium():
        return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16)

    @staticmethod
    def gpt_1p3b():
        # the BASELINE GPT-3 1.3B config
        return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                         max_seq_len=2048)

    @staticmethod
    def tiny(vocab=128, hidden=64, layers=2, heads=4, seq=64):
        return GPTConfig(vocab_size=vocab, hidden_size=hidden,
                         num_layers=layers, num_heads=heads, max_seq_len=seq)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        init = nn.initializer.Normal(0.0, config.initializer_range)
        bias_attr = None if config.use_bias else False
        # fused qkv: one [h, 3h] matmul
        self.qkv_proj = nn.Linear(config.hidden_size, 3 * config.hidden_size,
                                  weight_attr=nn.ParamAttr(initializer=init),
                                  bias_attr=bias_attr)
        self.out_proj = nn.Linear(config.hidden_size, config.hidden_size,
                                  weight_attr=nn.ParamAttr(initializer=init),
                                  bias_attr=bias_attr)
        self.dropout = config.dropout
        # Megatron tensor-parallel shardings when an mp axis is active:
        # qkv column-parallel, out row-parallel (mp_layers.py pattern)
        from jax.sharding import PartitionSpec as P

        if _mp_degree() > 1 and config.hidden_size % _mp_degree() == 0:
            self.qkv_proj.weight.dist_spec = P(None, "mp")
            if self.qkv_proj.bias is not None:
                self.qkv_proj.bias.dist_spec = P("mp")
            self.out_proj.weight.dist_spec = P("mp", None)

    def forward(self, x, cache=None):
        B, S, H = x.shape
        qkv = self.qkv_proj(x)  # [B,S,3H]
        qkv = mp.reshape(qkv, [B, S, 3, self.num_heads, self.head_dim])
        q, k, v = mp.unbind(qkv, axis=2)
        if cache is not None:
            k = mp.concat([cache[0], k], axis=1)
            v = mp.concat([cache[1], v], axis=1)
            new_cache = (k, v)
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=cache is None, dropout_p=self.dropout,
            training=self.training)
        out = mp.reshape(out, [B, S, H])
        out = self.out_proj(out)
        if cache is not None:
            return out, new_cache
        return out

    def _qkv_heads(self, x, mp_axis, lora=None, layer=None):
        """Project to per-head q/k/v `[B, S, heads, D]`. Unsharded:
        the fused `[H, 3H]` matmul (3-major reshape, unchanged).
        Under tensor parallel (`mp_axis` set) the serving engine binds
        this layer's qkv weight HEAD-GROUPED as `[H, heads/mp, 3, D]`
        (bias `[heads/mp, 3, D]`): the same full-length dot products
        produce just this shard's heads — column parallelism, so every
        float op is identical to mp=1 and token parity is exact.
        With `lora` (an `ops.lora.LoraState` — multi-tenant adapter
        serving) each slot's per-tenant low-rank qkv delta is added in
        the projection's own layout before the unbind; adapter id 0
        contributes exact zeros."""
        B, S, H = x.shape
        if mp_axis is None:
            qkv = self.qkv_proj(x)
            qkv = mp.reshape(qkv,
                             [B, S, 3, self.num_heads, self.head_dim])
            if lora is not None:
                qkv = qkv + lora.qkv_delta(x, layer, head_major=False)
            return mp.unbind(qkv, axis=2)
        from paddle_tpu.ops import nn_ops

        w, b = self.qkv_proj.weight, self.qkv_proj.bias
        lh = w.shape[1]                    # heads on this shard
        qkv = nn_ops.linear(
            x, mp.reshape(w, [H, lh * 3 * self.head_dim]),
            None if b is None
            else mp.reshape(b, [lh * 3 * self.head_dim]))
        qkv = mp.reshape(qkv, [B, S, lh, 3, self.head_dim])
        if lora is not None:
            # the B pages are head-sharded exactly like the qkv weight
            # (_tp_plan layout), so the shard's delta covers ITS heads
            qkv = qkv + lora.qkv_delta(x, layer, head_major=True)
        return mp.unbind(qkv, axis=3)

    def _attn_out(self, out, B, S, mp_axis, lora=None, layer=None):
        """Merge heads and apply the output projection. Under tensor
        parallel the shard's heads are all-gathered to the full
        `[B, S, H]` activation first, and out_proj (bound
        column-sharded `[H, H/mp]`) is followed by a second gather —
        full-length dots + exact concats, never a partial-sum psum, so
        the result is bit-identical to mp=1 (see DESIGN_DECISIONS
        "Tensor-parallel sharded serving"). The per-tenant `lora`
        delta adds to the (output-sharded) projection before the final
        gather — same input, same column slice, no extra collective."""
        out = mp.reshape(out, [B, S, -1])
        if mp_axis is not None:
            out = _mp_all_gather(out, mp_axis)
        proj = self.out_proj(out)
        if lora is not None:
            proj = proj + lora.linear_delta("out", out, layer)
        if mp_axis is not None:
            proj = _mp_all_gather(proj, mp_axis)
        return proj

    def forward_prefill(self, x, mp_axis=None, lora=None, layer=None):
        """Causal forward that ALSO returns this layer's k/v for the
        whole (padded) buffer — fills the fixed-size decode cache.
        Under tensor parallel the returned k/v carry only this shard's
        heads (they feed the shard's pool plane)."""
        B, S, H = x.shape
        q, k, v = self._qkv_heads(x, mp_axis, lora=lora, layer=layer)
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True, dropout_p=0.0, training=False)
        return self._attn_out(out, B, S, mp_axis, lora=lora,
                              layer=layer), k, v

    def forward_prefill_chunk(self, x, kpool, vpool, layer_idx,
                              block_row, start, plen, mp_axis=None,
                              kv_scales=None, lora=None):
        """Chunked prefill for ONE slot against the paged pool: write
        this chunk's k/v through the slot's block table and attend the
        chunk's queries over the whole context so far (shared prefix
        blocks included, read-only). x [1,C,H]; start/plen traced
        scalars — one compiled program per chunk WIDTH, not per prompt
        length. Returns (out [1,C,H], new_kpool, new_vpool), plus the
        updated per-block scale array when `kv_scales` rides along
        (int8 KV serving)."""
        from paddle_tpu.ops.paged_attention import paged_prefill_chunk

        B, C, H = x.shape  # B == 1
        q, k, v = self._qkv_heads(x, mp_axis, lora=lora,
                                  layer=layer_idx)
        if kv_scales is not None:
            out, kpool, vpool, kv_scales = paged_prefill_chunk(
                q, k, v, kpool, vpool, layer_idx, block_row, start,
                plen, scales=kv_scales, mp_axis=mp_axis)
            return (self._attn_out(out, B, C, mp_axis, lora=lora,
                                   layer=layer_idx), kpool, vpool,
                    kv_scales)
        out, kpool, vpool = paged_prefill_chunk(
            q, k, v, kpool, vpool, layer_idx, block_row, start, plen)
        return self._attn_out(out, B, C, mp_axis, lora=lora,
                              layer=layer_idx), kpool, vpool

    def forward_decode(self, x, kcache, vcache, pos):
        """One-token decode against a FIXED-size cache (the jit-friendly
        KV cache: no growing concat). x [B,1,H]; kcache/vcache
        [B,L,heads,D]; pos may be a traced scalar — or a [B] vector of
        per-row positions (the continuous-batching shape: each slot sits
        at its own depth). Writes this token's k/v at `pos`, attends
        over positions <= pos (additive mask), returns
        (out [B,1,H], new_kcache, new_vcache)."""
        import paddle_tpu as paddle

        B, S, H = x.shape  # S == 1
        L = kcache.shape[1]
        qkv = self.qkv_proj(x)
        qkv = mp.reshape(qkv, [B, 1, 3, self.num_heads, self.head_dim])
        q, k, v = mp.unbind(qkv, axis=2)        # [B,1,heads,D]
        per_row = getattr(pos, "ndim", 0) == 1  # [B] vector of positions
        posv = mp.reshape(pos, [B, 1]) if per_row else pos
        slot = (paddle.arange(L).unsqueeze(0) == posv).reshape(
            [-1, L, 1, 1])                      # [B or 1, L, 1, 1]
        kcache = paddle.where(slot, k, kcache)
        vcache = paddle.where(slot, v, vcache)
        # additive mask over the buffer: future slots (and the padded
        # tail) are -inf
        allowed = (paddle.arange(L).unsqueeze(0) <= posv)  # [B or 1, L]
        attn_mask = paddle.where(
            allowed, paddle.zeros([1, L]),
            paddle.full([1, L], -1e30)).reshape([-1, 1, 1, L])
        out = F.scaled_dot_product_attention(
            q, kcache, vcache, attn_mask=attn_mask, dropout_p=0.0,
            training=False)
        return (self.out_proj(mp.reshape(out, [B, 1, H])), kcache,
                vcache)

    def forward_decode_paged(self, x, kpool, vpool, layer_idx,
                             block_tables, positions, backend="auto",
                             mp_axis=None, kv_scales=None, lora=None):
        """Batched one-token decode against the GLOBAL paged KV pool
        (the continuous-batching engine's layer step). x [slots,1,H];
        kpool/vpool [layers, num_blocks, block_size, heads, D];
        positions [slots] per-slot absolute positions; block_tables
        [slots, max_blocks]; backend is the paged-attention kernel
        selector (`auto`/`dense`/`pallas` — ops/paged_attention.py).
        With `mp_axis` set (inside the engine's shard_map step) the
        pools and q/k/v carry heads/mp heads; the attention op is
        head-count agnostic, so both backends run per-shard unchanged.
        With `kv_scales` (int8 KV serving) the pools are int8 and the
        updated `[L, blocks, 2]` scale array returns as a 4th output.
        With `lora` (multi-tenant adapter serving) each slot's tenant
        delta fuses into the qkv and out projections.
        Returns (out, new_kpool, new_vpool[, new_kv_scales])."""
        from paddle_tpu.ops.paged_attention import paged_attention_step

        B, S, H = x.shape  # S == 1
        q, k, v = self._qkv_heads(x, mp_axis, lora=lora,
                                  layer=layer_idx)
        if kv_scales is not None:
            out, kpool, vpool, kv_scales = paged_attention_step(
                q, k, v, kpool, vpool, layer_idx, block_tables,
                positions, backend=backend, scales=kv_scales,
                mp_axis=mp_axis)
            return (self._attn_out(out, B, 1, mp_axis, lora=lora,
                                   layer=layer_idx), kpool, vpool,
                    kv_scales)
        out, kpool, vpool = paged_attention_step(
            q, k, v, kpool, vpool, layer_idx, block_tables, positions,
            backend=backend)
        return self._attn_out(out, B, 1, mp_axis, lora=lora,
                              layer=layer_idx), kpool, vpool

    def forward_verify_paged(self, x, kpool, vpool, layer_idx,
                             block_tables, positions, draft_lens,
                             backend="auto", mp_axis=None,
                             kv_scales=None, lora=None):
        """Speculative K-token verify over the GLOBAL paged pool: one
        fixed `[slots, W]` window per lane (W = K+1: the feed token
        plus the drafts). x [slots,W,H]; positions [slots] absolute
        position of window row 0 per slot; draft_lens [slots] live-row
        count minus one (rows past it write the null block). Writes
        every live row's k/v through the table and attends each window
        query causally up to its own position — the target model
        scores all W candidate positions in one pass. Returns
        (out [slots,W,H], new_kpool, new_vpool), plus the updated
        scale array under int8 KV serving (`kv_scales`). `lora` fuses
        each slot's tenant delta into the projections, same as the
        decode step — the verify window scores under the ADAPTED
        model, so speculative acceptance stays exact per tenant."""
        from paddle_tpu.ops.paged_attention import paged_verify_window

        B, W, H = x.shape
        q, k, v = self._qkv_heads(x, mp_axis, lora=lora,
                                  layer=layer_idx)
        if kv_scales is not None:
            out, kpool, vpool, kv_scales = paged_verify_window(
                q, k, v, kpool, vpool, layer_idx, block_tables,
                positions, draft_lens, backend=backend,
                scales=kv_scales, mp_axis=mp_axis)
            return (self._attn_out(out, B, W, mp_axis, lora=lora,
                                   layer=layer_idx), kpool, vpool,
                    kv_scales)
        out, kpool, vpool = paged_verify_window(
            q, k, v, kpool, vpool, layer_idx, block_tables, positions,
            draft_lens, backend=backend)
        return self._attn_out(out, B, W, mp_axis, lora=lora,
                              layer=layer_idx), kpool, vpool


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        init = nn.initializer.Normal(0.0, config.initializer_range)
        out_init = nn.initializer.Normal(
            0.0, config.initializer_range / math.sqrt(2 * config.num_layers))
        bias_attr = None if config.use_bias else False
        self.fc1 = nn.Linear(config.hidden_size, config.intermediate_size,
                             weight_attr=nn.ParamAttr(initializer=init),
                             bias_attr=bias_attr)
        self.fc2 = nn.Linear(config.intermediate_size, config.hidden_size,
                             weight_attr=nn.ParamAttr(initializer=out_init),
                             bias_attr=bias_attr)
        self.dropout = nn.Dropout(config.dropout)
        from jax.sharding import PartitionSpec as P

        if _mp_degree() > 1 and config.intermediate_size % _mp_degree() == 0:
            self.fc1.weight.dist_spec = P(None, "mp")
            if self.fc1.bias is not None:
                self.fc1.bias.dist_spec = P("mp")
            self.fc2.weight.dist_spec = P("mp", None)

    def forward(self, x, mp_axis=None, lora=None, layer=None):
        """Under tensor parallel (`mp_axis` set, serving engine's
        shard_map step) fc1 AND fc2 are bound column-sharded
        (`[H, I/mp]` / `[I, H/mp]`): each shard's outputs are
        full-length dots over the gathered input, concatenated by a
        tiled all-gather — exact column parallelism both times, never
        a partial-sum psum, so mp=N output is bit-identical to mp=1.
        The per-tenant `lora` deltas add to the (output-sharded) fc1
        pre-activation and fc2 output — same inputs, same column
        slices, no extra collective (adapter id 0 adds exact zeros)."""
        pre = self.fc1(x)
        if lora is not None:
            pre = pre + lora.linear_delta("fc1", x, layer)
        h = F.gelu(pre, approximate=True)
        if mp_axis is not None:
            h = _mp_all_gather(h, mp_axis)
        out = self.fc2(h)
        if lora is not None:
            out = out + lora.linear_delta("fc2", h, layer)
        if mp_axis is not None:
            out = _mp_all_gather(out, mp_axis)
        return self.dropout(out)


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(config.hidden_size,
                                epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln2 = nn.LayerNorm(config.hidden_size,
                                epsilon=config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)

    def forward(self, x, cache=None):
        if cache is not None:
            a, new_cache = self.attn(self.ln1(x), cache=cache)
            x = x + a
            x = x + self.mlp(self.ln2(x))
            return x, new_cache
        x = x + self.attn(self.ln1(x))
        x = x + self.mlp(self.ln2(x))
        return x

    def forward_prefill(self, x, mp_axis=None, lora=None, layer=None):
        a, k, v = self.attn.forward_prefill(self.ln1(x),
                                            mp_axis=mp_axis,
                                            lora=lora, layer=layer)
        x = x + a
        return x + self.mlp(self.ln2(x), mp_axis=mp_axis, lora=lora,
                            layer=layer), k, v

    def forward_prefill_chunk(self, x, kpool, vpool, layer_idx,
                              block_row, start, plen, mp_axis=None,
                              kv_scales=None, lora=None):
        if kv_scales is not None:
            a, kpool, vpool, kv_scales = self.attn.forward_prefill_chunk(
                self.ln1(x), kpool, vpool, layer_idx, block_row,
                start, plen, mp_axis=mp_axis, kv_scales=kv_scales,
                lora=lora)
            x = x + a
            return (x + self.mlp(self.ln2(x), mp_axis=mp_axis,
                                 lora=lora, layer=layer_idx), kpool,
                    vpool, kv_scales)
        a, kpool, vpool = self.attn.forward_prefill_chunk(
            self.ln1(x), kpool, vpool, layer_idx, block_row, start,
            plen, mp_axis=mp_axis, lora=lora)
        x = x + a
        return (x + self.mlp(self.ln2(x), mp_axis=mp_axis, lora=lora,
                             layer=layer_idx), kpool,
                vpool)

    def forward_decode(self, x, kcache, vcache, pos):
        a, kcache, vcache = self.attn.forward_decode(self.ln1(x),
                                                     kcache, vcache,
                                                     pos)
        x = x + a
        return x + self.mlp(self.ln2(x)), kcache, vcache

    def forward_decode_paged(self, x, kpool, vpool, layer_idx,
                             block_tables, positions, backend="auto",
                             mp_axis=None, kv_scales=None, lora=None):
        if kv_scales is not None:
            a, kpool, vpool, kv_scales = self.attn.forward_decode_paged(
                self.ln1(x), kpool, vpool, layer_idx, block_tables,
                positions, backend=backend, mp_axis=mp_axis,
                kv_scales=kv_scales, lora=lora)
            x = x + a
            return (x + self.mlp(self.ln2(x), mp_axis=mp_axis,
                                 lora=lora, layer=layer_idx), kpool,
                    vpool, kv_scales)
        a, kpool, vpool = self.attn.forward_decode_paged(
            self.ln1(x), kpool, vpool, layer_idx, block_tables,
            positions, backend=backend, mp_axis=mp_axis, lora=lora)
        x = x + a
        return (x + self.mlp(self.ln2(x), mp_axis=mp_axis, lora=lora,
                             layer=layer_idx), kpool,
                vpool)

    def forward_verify_paged(self, x, kpool, vpool, layer_idx,
                             block_tables, positions, draft_lens,
                             backend="auto", mp_axis=None,
                             kv_scales=None, lora=None):
        if kv_scales is not None:
            a, kpool, vpool, kv_scales = self.attn.forward_verify_paged(
                self.ln1(x), kpool, vpool, layer_idx, block_tables,
                positions, draft_lens, backend=backend,
                mp_axis=mp_axis, kv_scales=kv_scales, lora=lora)
            x = x + a
            return (x + self.mlp(self.ln2(x), mp_axis=mp_axis,
                                 lora=lora, layer=layer_idx), kpool,
                    vpool, kv_scales)
        a, kpool, vpool = self.attn.forward_verify_paged(
            self.ln1(x), kpool, vpool, layer_idx, block_tables,
            positions, draft_lens, backend=backend, mp_axis=mp_axis,
            lora=lora)
        x = x + a
        return (x + self.mlp(self.ln2(x), mp_axis=mp_axis, lora=lora,
                             layer=layer_idx), kpool,
                vpool)


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.wpe = nn.Embedding(config.max_seq_len, config.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.drop = nn.Dropout(config.dropout)
        self.blocks = nn.LayerList([GPTBlock(config)
                                    for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None):
        B, S = input_ids.shape
        if position_ids is None:
            position_ids = paddle.arange(S, dtype="int32")
        h = self.wte(input_ids) + self.wpe(position_ids)
        h = self.drop(h)
        for blk in self.blocks:
            h = blk(h)
        return self.ln_f(h)

    def _embed(self, token_ids, mp_axis):
        """Token embedding; under tensor parallel the wte table is
        bound vocab-sharded `[V/mp, H]` and the lookup goes through the
        masked-gather + psum (exact) vocab-parallel path."""
        if mp_axis is None:
            return self.wte(token_ids)
        return _vocab_parallel_embed(self.wte.weight, token_ids,
                                     mp_axis)

    def forward_prefill(self, input_ids, mp_axis=None, lora=None):
        """Fill the decode caches: causal forward over the (padded)
        buffer, collecting per-layer k/v stacked on a leading layer
        axis (single Tensors, so a compiled decode loop carries them).
        Under tensor parallel the stacks carry this shard's heads."""
        B, S = input_ids.shape
        h = self._embed(input_ids, mp_axis) + self.wpe(
            paddle.arange(S, dtype="int32"))
        ks, vs = [], []
        for i, blk in enumerate(self.blocks):
            h, k, v = blk.forward_prefill(h, mp_axis=mp_axis,
                                          lora=lora, layer=i)
            ks.append(k)
            vs.append(v)
        return self.ln_f(h), mp.stack(ks, axis=0), mp.stack(vs, axis=0)

    def forward_prefill_chunk(self, token_ids, start, kpool, vpool,
                              block_row, plen, mp_axis=None,
                              kv_scales=None, lora=None):
        """Chunked paged prefill (the engine's incremental admission
        path): token_ids [1,C] — chunk `[start, start+C)` of one
        slot's prompt, padded past `plen`; kpool/vpool the global
        paged pools; block_row [max_blocks] the slot's table. Writes
        the chunk's per-layer KV through the table and returns
        (hidden [1,C,H], new_kpool, new_vpool). `start`/`plen` are
        traced — ONE compiled program serves every chunk of every
        prompt, so prefill trace count is bounded by the chunk shape,
        not a bucket ladder."""
        B, C = token_ids.shape
        pos_t = start.astype("int32") if hasattr(start, "astype") \
            else paddle.to_tensor(start, dtype="int32")
        # clamp padded-tail positions into the wpe table: their rows
        # are garbage the engine ignores, but the gather must stay in
        # bounds for any (start, chunk) combination
        pos_vec = paddle.clip(pos_t + paddle.arange(C, dtype="int32"),
                              0, self.config.max_seq_len - 1)
        h = self._embed(token_ids, mp_axis) \
            + self.wpe(pos_vec).unsqueeze(0)
        if kv_scales is not None:
            for i, blk in enumerate(self.blocks):
                h, kpool, vpool, kv_scales = blk.forward_prefill_chunk(
                    h, kpool, vpool, i, block_row, pos_t, plen,
                    mp_axis=mp_axis, kv_scales=kv_scales, lora=lora)
            return self.ln_f(h), kpool, vpool, kv_scales
        for i, blk in enumerate(self.blocks):
            h, kpool, vpool = blk.forward_prefill_chunk(
                h, kpool, vpool, i, block_row, pos_t, plen,
                mp_axis=mp_axis, lora=lora)
        return self.ln_f(h), kpool, vpool

    def forward_decode(self, token_ids, pos, kstack, vstack):
        """One decode step: token_ids [B,1], pos scalar (may be traced)
        or [B] per-row positions, kstack/vstack
        [num_layers, B, L, heads, D]. Returns
        (hidden [B,1,H], new_kstack, new_vstack)."""
        pos_t = pos.astype("int32") if hasattr(pos, "astype") \
            else paddle.to_tensor(pos, dtype="int32")
        if getattr(pos_t, "ndim", 0) == 1:      # per-row: [B] -> [B,1,H]
            pemb = self.wpe(pos_t).unsqueeze(1)
        else:
            pemb = self.wpe(mp.reshape(pos_t, [1]))
        h = self.wte(token_ids) + pemb
        nks, nvs = [], []
        for i, blk in enumerate(self.blocks):
            h, nk, nv = blk.forward_decode(h, kstack[i], vstack[i], pos)
            nks.append(nk)
            nvs.append(nv)
        return (self.ln_f(h), mp.stack(nks, axis=0),
                mp.stack(nvs, axis=0))

    def forward_decode_paged(self, token_ids, positions, kpool, vpool,
                             block_tables, backend="auto",
                             mp_axis=None, kv_scales=None, lora=None):
        """Batched decode step over the paged pool (continuous-batching
        engine path): token_ids [slots,1], positions [slots] int32
        per-slot absolute positions, kpool/vpool
        [num_layers, num_blocks, block_size, heads, D], block_tables
        [slots, max_blocks], backend the paged-attention kernel
        selector (`auto`/`dense`/`pallas`, resolved per layer step in
        ops/paged_attention.py). Returns (hidden [slots,1,H],
        new_kpool, new_vpool) — pool updates chain functionally through
        the layers and alias in place under the engine's donated
        compiled step."""
        pos_t = positions.astype("int32") if hasattr(positions, "astype") \
            else paddle.to_tensor(positions, dtype="int32")
        h = self._embed(token_ids, mp_axis) \
            + self.wpe(pos_t).unsqueeze(1)
        if kv_scales is not None:
            for i, blk in enumerate(self.blocks):
                h, kpool, vpool, kv_scales = blk.forward_decode_paged(
                    h, kpool, vpool, i, block_tables, pos_t,
                    backend=backend, mp_axis=mp_axis,
                    kv_scales=kv_scales, lora=lora)
            return self.ln_f(h), kpool, vpool, kv_scales
        for i, blk in enumerate(self.blocks):
            h, kpool, vpool = blk.forward_decode_paged(
                h, kpool, vpool, i, block_tables, pos_t,
                backend=backend, mp_axis=mp_axis, lora=lora)
        return self.ln_f(h), kpool, vpool

    def forward_verify_paged(self, token_ids, positions, draft_lens,
                             kpool, vpool, block_tables,
                             backend="auto", mp_axis=None,
                             kv_scales=None, lora=None):
        """Speculative verify step over the paged pool (the engine's
        K-token decode): token_ids [slots, W] — the feed token plus up
        to W-1 drafted tokens per lane, positions [slots] int32 row-0
        absolute positions, draft_lens [slots] int32 live-row bounds
        (both traced — ONE compiled program per (backend, W) serves
        every draft/acceptance mix), kpool/vpool the global pools,
        block_tables [slots, max_blocks]. Returns
        (hidden [slots, W, H], new_kpool, new_vpool) — the hidden at
        every window row, so the caller argmaxes all W candidate
        continuations from one pass."""
        B, W = token_ids.shape
        pos_t = positions.astype("int32") \
            if hasattr(positions, "astype") \
            else paddle.to_tensor(positions, dtype="int32")
        dlen_t = draft_lens.astype("int32") \
            if hasattr(draft_lens, "astype") \
            else paddle.to_tensor(draft_lens, dtype="int32")
        # absolute position per window row, clipped into the wpe table:
        # dead rows past a slot's draft length may run beyond the
        # model's positions — their rows are garbage the engine
        # ignores, but the gather must stay in bounds
        wpos = paddle.clip(
            pos_t.unsqueeze(1)
            + paddle.arange(W, dtype="int32").unsqueeze(0),
            0, self.config.max_seq_len - 1)            # [B, W]
        h = self._embed(token_ids, mp_axis) + self.wpe(wpos)
        if kv_scales is not None:
            for i, blk in enumerate(self.blocks):
                h, kpool, vpool, kv_scales = blk.forward_verify_paged(
                    h, kpool, vpool, i, block_tables, pos_t, dlen_t,
                    backend=backend, mp_axis=mp_axis,
                    kv_scales=kv_scales, lora=lora)
            return self.ln_f(h), kpool, vpool, kv_scales
        for i, blk in enumerate(self.blocks):
            h, kpool, vpool = blk.forward_verify_paged(
                h, kpool, vpool, i, block_tables, pos_t, dlen_t,
                backend=backend, mp_axis=mp_axis, lora=lora)
        return self.ln_f(h), kpool, vpool


def _transformed_method(cls, name):
    """Lazily dy2static-transform an unbound method ONCE per class (the
    transform is source-level; callers get a cached converted function
    whose tensor-`while` loops run as lax.while_loop under any trace)."""
    cache_name = f"_{name}_jst"
    fn = cls.__dict__.get(cache_name)
    if fn is None:
        from paddle_tpu.jit.dy2static import transform_function

        fn = transform_function(getattr(cls, name))
        setattr(cls, cache_name, staticmethod(fn))
    return fn


class GPTForCausalLM(nn.Layer):
    """LM head ties to wte (SharedLayerDesc analog, pp_layers.py:77)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)
        self.config = config

    def forward(self, input_ids, labels=None):
        h = self.gpt(input_ids)
        # tied embedding projection: [B,S,H] @ [V,H]^T
        logits = paddle.matmul(h, self.gpt.wte.weight, transpose_y=True)
        if labels is not None:
            loss = F.cross_entropy(
                mp.reshape(logits, [-1, self.config.vocab_size]),
                mp.reshape(labels, [-1]))
            return loss
        return logits

    def loss_fn(self, logits, labels):
        return F.cross_entropy(
            mp.reshape(logits, [-1, self.config.vocab_size]),
            mp.reshape(labels, [-1]))

    def generate(self, input_ids, max_length=None, eos_token_id=None,
                 use_cache=False):
        """Greedy decode (generation_utils GenerationMixin.greedy_search
        analog). Written as a data-dependent `while` over a fixed-size
        token buffer so that under @to_static the WHOLE decode compiles
        to ONE program with a lax.while_loop inside (dy2static
        convert_while_loop — the run-to-completion decode loop); eager
        calls run the same code as a python loop.

        use_cache=False re-runs the causal forward over the buffer per
        token (correctness-first); use_cache=True is the fixed-buffer
        KV-cache path (forward_prefill + per-token forward_decode — the
        layer caches are stacked Tensors so the compiled loop carries
        them; O(prefix) per token instead of O(prefix^2)). Compiling
        the cached loop for a very deep model is a significant one-time
        cost through remote-compile setups (the whole 24-layer step is
        one program); small/medium configs compile in seconds.

        input_ids [B, S0] -> tokens [B, max_length] (positions past an
        early EOS keep repeating EOS because `done` rows freeze).

        Generation is an eval-mode operation: with use_cache=True and
        active dropout the cached path (which never applies dropout)
        would diverge from the plain path, so it refuses."""
        max_length = max_length or self.config.max_seq_len
        B, S0 = input_ids.shape
        if max_length < S0:
            raise ValueError(f"max_length={max_length} < prompt {S0}")
        if use_cache and self.training and self.config.dropout > 0:
            raise ValueError(
                "generate(use_cache=True) is deterministic (no dropout) "
                "— call model.eval() first")
        # route through dy2static-transformed bodies so the decode
        # while converts to lax.while_loop even when generate is CALLED
        # from inside a larger traced function (not itself the
        # to_static entry point)
        impl = _transformed_method(
            type(self),
            "_generate_cached" if use_cache else "_generate_plain")
        return impl(self, input_ids, max_length, eos_token_id)

    def _generate_plain(self, input_ids, max_length, eos_token_id):
        import paddle_tpu as paddle

        B, S0 = input_ids.shape
        pad = paddle.zeros([B, max_length - S0], dtype=input_ids.dtype)
        tokens = mp.concat([input_ids, pad], axis=1)      # [B, L] static
        positions = paddle.arange(max_length)             # [L]
        # `done` derives from the (possibly traced) input so the loop
        # condition is tensor-dependent from the first evaluation
        done = (input_ids.sum(axis=1) * 0).astype("bool")  # [B] False
        pos = S0
        while paddle.logical_and(paddle.logical_not(done.all()),
                                 paddle.to_tensor(pos < max_length)):
            logits = self.forward(tokens)                 # [B, L, V]
            # logits at pos-1 decide the token at pos (one-hot reduce:
            # index `pos` is a traced scalar inside the compiled loop)
            sel = (positions == (pos - 1)).astype(logits.dtype)
            step_logits = (logits * sel.unsqueeze(0).unsqueeze(-1)) \
                .sum(axis=1)                              # [B, V]
            nxt = step_logits.argmax(axis=-1).astype(input_ids.dtype)
            if eos_token_id is not None:
                eos = paddle.full([1], eos_token_id, input_ids.dtype)
                nxt = paddle.where(done, eos.expand([B]), nxt)
                done = paddle.logical_or(done, nxt == eos_token_id)
            write = (positions == pos).unsqueeze(0)       # [1, L]
            tokens = paddle.where(write, nxt.unsqueeze(-1), tokens)
            pos = pos + 1
        return tokens

    def _logits_of(self, hidden, mp_axis=None):
        """Tied-embedding logits. Under tensor parallel the wte table
        is bound vocab-sharded, so each shard computes its `[.., V/mp]`
        logit columns with full-length dots; ONE tiled all-gather
        assembles the full logits (replicated on every shard) for the
        host's greedy argmax / speculative acceptance — exact, where a
        sharded-argmax psum would save bandwidth but lose the simple
        "full logits on host" contract (DESIGN_DECISIONS r12)."""
        logits = paddle.matmul(hidden, self.gpt.wte.weight,
                               transpose_y=True)
        if mp_axis is not None:
            logits = _mp_all_gather(logits, mp_axis)
        return logits

    def _generate_cached(self, input_ids, max_length, eos_token_id):
        import paddle_tpu as paddle

        B, S0 = input_ids.shape
        L = max_length
        pad = paddle.zeros([B, L - S0], dtype=input_ids.dtype)
        tokens = mp.concat([input_ids, pad], axis=1)
        positions = paddle.arange(L)
        # prefill over the PROMPT only (O(S0^2) attention, not O(L^2));
        # cache buffers zero-pad to L — every slot >= S0 is overwritten
        # before it is ever attended (the decode mask is <= pos)
        hidden, kstack, vstack = self.gpt.forward_prefill(input_ids)
        def pad_cache(c):
            z = paddle.zeros(list(c.shape[:2]) + [L - S0] +
                             list(c.shape[3:]), dtype=c.dtype)
            return mp.concat([c, z], axis=2)

        kstack = pad_cache(kstack)
        vstack = pad_cache(vstack)
        # only the last prompt position's logits matter: reduce hidden
        # to [B,H] BEFORE the vocab projection (1/L the matmul)
        first_logits = self._logits_of(hidden[:, S0 - 1])
        cur = first_logits.argmax(axis=-1).astype(input_ids.dtype)
        done = (input_ids.sum(axis=1) * 0).astype("bool")
        if eos_token_id is not None:
            done = paddle.logical_or(done, cur == eos_token_id)
        tokens = paddle.where((positions == S0).unsqueeze(0),
                              cur.unsqueeze(-1), tokens)
        pos = S0
        # decode: token at `pos` goes in, token at pos+1 comes out
        # (h_step is a fresh name: the prefill `hidden` is [B,L,H] and
        # must not be carried against the loop's [B,1,H] activations)
        while paddle.logical_and(paddle.logical_not(done.all()),
                                 paddle.to_tensor(pos < L - 1)):
            h_step, kstack, vstack = self.gpt.forward_decode(
                cur.unsqueeze(-1), pos, kstack, vstack)
            nxt = self._logits_of(h_step)[:, 0].argmax(axis=-1) \
                .astype(tokens.dtype)
            if eos_token_id is not None:
                eos = paddle.full([1], eos_token_id, tokens.dtype)
                nxt = paddle.where(done, eos.expand([B]), nxt)
                done = paddle.logical_or(done, nxt == eos_token_id)
            tokens = paddle.where((positions == pos + 1).unsqueeze(0),
                                  nxt.unsqueeze(-1), tokens)
            cur = nxt
            pos = pos + 1
        return tokens

    def num_params(self):
        return sum(p.size for p in self.parameters())

    def flops_per_token(self, seq_len=None):
        """Approximate training FLOPs/token (6ND + attention)."""
        c = self.config
        n = self.num_params()
        s = seq_len or c.max_seq_len
        return 6 * n + 12 * c.num_layers * c.hidden_size * s


class GPTEmbeddingPipe(nn.Layer):
    """Embedding stage for the pipelined GPT (pp_layers.py SharedLayerDesc
    pattern: the same instance serves as the tied LM head)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.wpe = nn.Embedding(config.max_seq_len, config.hidden_size,
                                weight_attr=nn.ParamAttr(initializer=init))
        self.drop = nn.Dropout(config.dropout)
        from jax.sharding import PartitionSpec as P

        if _mp_degree() > 1 and config.vocab_size % _mp_degree() == 0:
            # vocab-parallel embedding (VocabParallelEmbedding analog)
            self.wte.weight.dist_spec = P("mp", None)

    def forward(self, input_ids):
        S = input_ids.shape[1]
        pos = paddle.arange(S, dtype="int32")
        return self.drop(self.wte(input_ids) + self.wpe(pos))


def _gpt_head_fwd(embed_layer: "GPTEmbeddingPipe", x):
    # tied projection: [B,S,H] @ wte^T
    return paddle.matmul(x, embed_layer.wte.weight, transpose_y=True)


class GPTFinalNorm(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)

    def forward(self, x):
        return self.ln_f(x)


def build_pipeline_gpt(config: GPTConfig, num_stages: int,
                       num_microbatches: int = None,
                       recompute_interval: int = 0):
    """GPT as a PipelineLayer: tied embedding/head via SharedLayerDesc,
    the block stack stage-stacked over the 'pp' mesh axis. The analog of
    the reference's GPTForPretrainingPipe-style models driven by
    hybrid_parallel_pp_transformer.py tests."""
    from paddle_tpu.distributed import (LayerDesc, PipelineLayer,
                                        SharedLayerDesc)

    descs = [
        SharedLayerDesc("gpt_embed", GPTEmbeddingPipe, None, "wte.weight",
                        config),
        *[LayerDesc(GPTBlock, config) for _ in range(config.num_layers)],
        LayerDesc(GPTFinalNorm, config),
        SharedLayerDesc("gpt_embed", GPTEmbeddingPipe, _gpt_head_fwd,
                        "wte.weight", config),
    ]
    return PipelineLayer(descs, num_stages=num_stages,
                         num_microbatches=num_microbatches,
                         recompute_interval=recompute_interval)
