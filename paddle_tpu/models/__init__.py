from .gpt import GPTConfig, GPTForCausalLM, GPTModel

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM"]
