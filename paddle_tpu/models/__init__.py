from .bert import (BertConfig, BertForPretraining,
                   BertForSequenceClassification, BertModel, ErnieModel)
from .gpt import GPTConfig, GPTForCausalLM, GPTModel
from .deep_fm import DeepFM
from .wide_deep import WideDeep

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "BertConfig",
           "BertModel", "ErnieModel", "BertForSequenceClassification",
           "BertForPretraining", "WideDeep", "DeepFM"]
