"""paddle.quantization analog (python/paddle/quantization/): QuantConfig
+ QAT (fake-quant with straight-through gradients) + PTQ (observer
calibration then convert).

TPU-native: fake-quant runs as jnp round/clip inside the same compiled
step as everything else (STE via PyLayer custom_vjp, which survives
tracing); converted inference layers store int8 weights + scales and
dequantize at the matmul edge, letting the MXU consume int8 where XLA
chooses to.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import paddle_tpu.nn as nn
from paddle_tpu.core.pylayer import PyLayer
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.dispatch import apply

__all__ = ["quantize_absmax", "dequantize", "fake_quant",
           "AbsmaxObserver", "FakeQuanterWithAbsMaxObserver",
           "QuantConfig", "QAT", "PTQ", "QuantedLinear"]


def quantize_absmax(w, bits=8, axis=None):
    """Symmetric absmax quantization. Returns (int8 array, scale).
    axis=None: per-tensor; axis=k: per-channel scales along k."""
    arr = w._array if isinstance(w, Tensor) else jnp.asarray(w)
    qmax = 2 ** (bits - 1) - 1
    if axis is None:
        scale = jnp.max(jnp.abs(arr)) / qmax
    else:
        red = tuple(i for i in range(arr.ndim) if i != axis)
        scale = (jnp.max(jnp.abs(arr), axis=red, keepdims=True) / qmax)
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(arr / scale), -qmax - 1, qmax).astype(jnp.int8)
    return q, scale


def dequantize(q, scale):
    return q.astype(jnp.float32) * scale


class _FakeQuantSTE(PyLayer):
    """Round-to-grid forward, identity gradient (the reference's
    fake_quantize_dequantize_abs_max op + its straight-through grad)."""

    @staticmethod
    def forward(ctx, x, scale, qmax):
        arr = x._array
        s = scale._array if isinstance(scale, Tensor) else scale
        q = jnp.clip(jnp.round(arr / s), -qmax - 1, qmax)
        return Tensor._wrap(q * s)

    @staticmethod
    def backward(ctx, dy):
        return dy  # STE


def fake_quant(x, scale, bits=8):
    qmax = 2 ** (bits - 1) - 1
    return _FakeQuantSTE.apply(x, scale, qmax)


class AbsmaxObserver(nn.Layer):
    """PTQ observer (observers/abs_max.py): tracks max |x| seen."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self._absmax = 0.0

    def forward(self, x):
        self._absmax = max(self._absmax,
                           float(jnp.max(jnp.abs(x._array))))
        return x

    def scale(self):
        qmax = 2 ** (self.quant_bits - 1) - 1
        return max(self._absmax, 1e-8) / qmax


class FakeQuanterWithAbsMaxObserver(nn.Layer):
    """QAT quanter (quanters/abs_max.py): moving-average absmax + STE
    fake-quant; the observed scale updates eagerly between steps."""

    def __init__(self, moving_rate=0.9, quant_bits=8):
        super().__init__()
        self.moving_rate = moving_rate
        self.quant_bits = quant_bits
        self._absmax = None

    def forward(self, x):
        import jax

        if not isinstance(x._array, jax.core.Tracer):
            # observation is an eager-side effect; inside a compiled step
            # the last observed scale is baked into the trace
            cur = float(jnp.max(jnp.abs(x._array)))
            self._absmax = cur if self._absmax is None else \
                self.moving_rate * self._absmax + \
                (1 - self.moving_rate) * cur
        qmax = 2 ** (self.quant_bits - 1) - 1
        scale = max(self._absmax or 1.0, 1e-8) / qmax
        return fake_quant(x, jnp.float32(scale), self.quant_bits)


class QuantConfig:
    """config.py:QuantConfig lite: one activation + one weight quanter
    factory applied to every quantizable layer."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight

    def _make(self, which):
        proto = self.activation if which == "a" else self.weight
        if proto is None:
            return None
        # factories are "quanter prototypes": instantiate per layer
        if isinstance(proto, type):
            return proto()
        return type(proto)(**{k: v for k, v in vars(proto).items()
                              if k in ("moving_rate", "quant_bits")})


class QATLinear(nn.Layer):
    """Training-time quantized Linear: fake-quant weight + activation."""

    def __init__(self, inner, a_quanter, w_quanter):
        super().__init__()
        self.inner = inner
        self.a_quanter = a_quanter
        self.w_quanter = w_quanter

    def forward(self, x):
        if self.a_quanter is not None:
            x = self.a_quanter(x)
        w = self.inner.weight
        if self.w_quanter is not None:
            w = self.w_quanter(w)
        out = x.matmul(w)
        if self.inner.bias is not None:
            out = out + self.inner.bias
        return out


class QuantedLinear(nn.Layer):
    """Inference-time converted Linear: int8 weight + scale, dequant at
    the matmul edge."""

    def __init__(self, linear, act_scale=None):
        super().__init__()
        self.qweight, self.wscale = quantize_absmax(linear.weight, axis=1)
        self.bias = linear.bias
        self.act_scale = act_scale
        self.weight_shape = list(linear.weight.shape)

    def forward(self, x):
        if self.act_scale is not None:
            # PTQ-calibrated activation quantization (round to the
            # observed int8 grid before the matmul)
            qmax = 127
            s = self.act_scale

            def aq(a):
                return jnp.clip(jnp.round(a / s), -qmax - 1, qmax) * s
            x = apply("quant_act", aq, x)
        w = dequantize(self.qweight, self.wscale)
        out = x.matmul(Tensor._wrap(w))
        if self.bias is not None:
            out = out + self.bias
        return out


def _replace_layers(model, predicate, factory):
    for name, child in list(model._sub_layers.items()):
        if predicate(child):
            setattr(model, name, factory(child))
        else:
            _replace_layers(child, predicate, factory)
    return model


class QAT:
    """qat.py:QAT — wrap quantizable layers with fake-quanters."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=True):
        cfg = self.config
        return _replace_layers(
            model, lambda l: isinstance(l, nn.Linear),
            lambda l: QATLinear(l, cfg._make("a"), cfg._make("w")))


class PTQ:
    """ptq.py:PTQ — observe activations, then convert to quantized
    inference layers."""

    class _Observed(nn.Layer):
        def __init__(self, inner, observer):
            super().__init__()
            self.inner = inner
            self.observer = observer

        def forward(self, x):
            if self.observer is not None:
                x = self.observer(x)
            return self.inner(x)

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig(activation=AbsmaxObserver,
                                            weight=None)

    def quantize(self, model, inplace=True):
        cfg = self.config
        return _replace_layers(
            model, lambda l: isinstance(l, nn.Linear),
            lambda l: PTQ._Observed(l, cfg._make("a")))

    def convert(self, model, inplace=True):
        return _replace_layers(
            model, lambda l: isinstance(l, PTQ._Observed),
            lambda l: QuantedLinear(
                l.inner,
                act_scale=l.observer.scale() if l.observer else None))
