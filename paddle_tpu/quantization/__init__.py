"""paddle.quantization analog (python/paddle/quantization/): QuantConfig
+ QAT (fake-quant with straight-through gradients) + PTQ (observer
calibration then convert).

TPU-native: fake-quant runs as jnp round/clip inside the same compiled
step as everything else (STE via PyLayer custom_vjp, which survives
tracing); converted inference layers store int8 weights + scales and
dequantize at the matmul edge, letting the MXU consume int8 where XLA
chooses to.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import paddle_tpu.nn as nn
from paddle_tpu.core.pylayer import PyLayer
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.dispatch import apply

__all__ = ["quantize_absmax", "dequantize", "fake_quant",
           "AbsmaxObserver", "PerChannelAbsmaxObserver",
           "FakeQuanterWithAbsMaxObserver",
           "FakeQuanterChannelWiseAbsMax",
           "QuantConfig", "QAT", "PTQ", "QuantedLinear",
           "QuantedConv2D", "QATLinear", "QATConv2D", "convert"]


def quantize_absmax(w, bits=8, axis=None):
    """Symmetric absmax quantization. Returns (int8 array, scale).
    axis=None: per-tensor; axis=k: per-channel scales along k."""
    arr = w._array if isinstance(w, Tensor) else jnp.asarray(w)
    qmax = 2 ** (bits - 1) - 1
    if axis is None:
        scale = jnp.max(jnp.abs(arr)) / qmax
    else:
        red = tuple(i for i in range(arr.ndim) if i != axis)
        scale = (jnp.max(jnp.abs(arr), axis=red, keepdims=True) / qmax)
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(arr / scale), -qmax - 1, qmax).astype(jnp.int8)
    return q, scale


def dequantize(q, scale, dtype=None):
    """Rebuild a float array from int8 values + scale. `dtype` is the
    OUTPUT dtype (default float32, the legacy contract): passing the
    model's compute dtype dequantizes straight to it — one multiply,
    no second cast at the call site (the int8 weight-serving path
    dequantizes per-tile inside the compiled step this way)."""
    dt = jnp.float32 if dtype is None else dtype
    return q.astype(dt) * jnp.asarray(scale).astype(dt)


class _FakeQuantSTE(PyLayer):
    """Round-to-grid forward, identity gradient (the reference's
    fake_quantize_dequantize_abs_max op + its straight-through grad)."""

    @staticmethod
    def forward(ctx, x, scale, qmax):
        arr = x._array
        s = scale._array if isinstance(scale, Tensor) else scale
        q = jnp.clip(jnp.round(arr / s), -qmax - 1, qmax)
        return Tensor._wrap(q * s)

    @staticmethod
    def backward(ctx, dy):
        return dy  # STE


def fake_quant(x, scale, bits=8):
    qmax = 2 ** (bits - 1) - 1
    return _FakeQuantSTE.apply(x, scale, qmax)


class AbsmaxObserver(nn.Layer):
    """PTQ observer (observers/abs_max.py): tracks max |x| seen."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self._absmax = 0.0

    def forward(self, x):
        self._absmax = max(self._absmax,
                           float(jnp.max(jnp.abs(x._array))))
        return x

    def scale(self):
        qmax = 2 ** (self.quant_bits - 1) - 1
        return max(self._absmax, 1e-8) / qmax


class PerChannelAbsmaxObserver(nn.Layer):
    """Per-channel PTQ observer (reference observers with quant_axis):
    tracks max |x| per channel along `channel_axis`. Defaults to axis 1
    — the feature/channel dim of [N, C, ...] activations and [N, in]
    linear inputs (axis 0 would be the BATCH dim: per-sample maxima
    that break when the batch size changes); pass axis 0 explicitly
    for OIHW weights."""

    def __init__(self, quant_bits=8, channel_axis=1):
        super().__init__()
        self.quant_bits = quant_bits
        self.channel_axis = channel_axis
        self._absmax = None

    def forward(self, x):
        arr = x._array
        red = tuple(i for i in range(arr.ndim) if i != self.channel_axis)
        cur = np.asarray(jnp.max(jnp.abs(arr), axis=red))
        self._absmax = cur if self._absmax is None \
            else np.maximum(self._absmax, cur)
        return x

    def scale(self):
        """Per-channel scale vector (shape [n_channels]); None before
        any observation (convert then skips activation quant, like the
        other observers)."""
        if self._absmax is None:
            return None
        qmax = 2 ** (self.quant_bits - 1) - 1
        return np.maximum(self._absmax, 1e-8) / qmax


class FakeQuanterChannelWiseAbsMax(nn.Layer):
    """Per-channel QAT weight quanter (reference
    quanters FakeQuanterChannelWiseAbsMaxObserver): per-channel absmax
    scale along `channel_axis` + STE fake-quant. channel_axis=None lets
    the wrapping QAT layer pick the layer-appropriate axis (Linear out
    dim 1, Conv2D out dim 0)."""

    def __init__(self, quant_bits=8, channel_axis=None, moving_rate=0.9):
        super().__init__()
        self.quant_bits = quant_bits
        self.channel_axis = channel_axis
        self.moving_rate = moving_rate
        self._absmax = None

    def forward(self, x):
        import jax

        if self.channel_axis is None:
            raise ValueError(
                "FakeQuanterChannelWiseAbsMax needs a channel_axis: as "
                "a weight quanter the QAT wrapper sets it (Linear=1, "
                "Conv2D=0); as an activation quanter pass it explicitly "
                "(axis 0 would be the BATCH dim — per-sample scales)")
        axis = self.channel_axis
        qmax = 2 ** (self.quant_bits - 1) - 1
        arr = x._array
        red = tuple(i for i in range(arr.ndim) if i != axis)
        if not isinstance(arr, jax.core.Tracer):
            cur = np.asarray(jnp.max(jnp.abs(arr), axis=red))
            self._absmax = cur if self._absmax is None else \
                self.moving_rate * self._absmax + \
                (1 - self.moving_rate) * cur
        absmax = self._absmax if self._absmax is not None \
            else np.ones(arr.shape[axis], np.float32)
        scale = np.maximum(absmax, 1e-8) / qmax
        shape = [1] * arr.ndim
        shape[axis] = -1
        return fake_quant(x, jnp.asarray(scale, jnp.float32).reshape(shape),
                          self.quant_bits)

    def scale(self):
        qmax = 2 ** (self.quant_bits - 1) - 1
        if self._absmax is None:
            return None
        return np.maximum(self._absmax, 1e-8) / qmax


class FakeQuanterWithAbsMaxObserver(nn.Layer):
    """QAT quanter (quanters/abs_max.py): moving-average absmax + STE
    fake-quant; the observed scale updates eagerly between steps."""

    def __init__(self, moving_rate=0.9, quant_bits=8):
        super().__init__()
        self.moving_rate = moving_rate
        self.quant_bits = quant_bits
        self._absmax = None

    def forward(self, x):
        import jax

        if not isinstance(x._array, jax.core.Tracer):
            # observation is an eager-side effect; inside a compiled step
            # the last observed scale is baked into the trace
            cur = float(jnp.max(jnp.abs(x._array)))
            self._absmax = cur if self._absmax is None else \
                self.moving_rate * self._absmax + \
                (1 - self.moving_rate) * cur
        return fake_quant(x, jnp.float32(self.scale()), self.quant_bits)

    def scale(self):
        qmax = 2 ** (self.quant_bits - 1) - 1
        return max(self._absmax or 1.0, 1e-8) / qmax


class QuantConfig:
    """config.py:QuantConfig lite: one activation + one weight quanter
    factory applied to every quantizable layer."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight

    def _make(self, which):
        proto = self.activation if which == "a" else self.weight
        if proto is None:
            return None
        # factories are "quanter prototypes": instantiate per layer
        if isinstance(proto, type):
            return proto()
        return type(proto)(**{k: v for k, v in vars(proto).items()
                              if k in ("moving_rate", "quant_bits",
                                       "channel_axis")})


class QATLinear(nn.Layer):
    """Training-time quantized Linear: fake-quant weight + activation."""

    def __init__(self, inner, a_quanter, w_quanter):
        super().__init__()
        self.inner = inner
        self.a_quanter = a_quanter
        self.w_quanter = w_quanter
        if getattr(w_quanter, "channel_axis", 0) is None:
            w_quanter.channel_axis = 1  # Linear weight [in, out]: out dim

    def forward(self, x):
        if self.a_quanter is not None:
            x = self.a_quanter(x)
        w = self.inner.weight
        if self.w_quanter is not None:
            w = self.w_quanter(w)
        out = x.matmul(w)
        if self.inner.bias is not None:
            out = out + self.inner.bias
        return out


class QATConv2D(nn.Layer):
    """Training-time quantized Conv2D (reference nn/quant QuantedConv2D
    training form): fake-quant input activation + weight, then the
    exact conv the wrapped layer would run."""

    def __init__(self, inner, a_quanter, w_quanter):
        super().__init__()
        self.inner = inner
        self.a_quanter = a_quanter
        self.w_quanter = w_quanter
        if getattr(w_quanter, "channel_axis", 0) is None:
            w_quanter.channel_axis = 0  # conv weight [out, in, kh, kw]

    def forward(self, x):
        from paddle_tpu.ops import nn_ops

        if self.a_quanter is not None:
            x = self.a_quanter(x)
        w = self.inner.weight
        if self.w_quanter is not None:
            w = self.w_quanter(w)
        c = self.inner
        return nn_ops.conv2d(x, w, c.bias, c._stride, c._padding,
                             c._dilation, c._groups, c._data_format)


class _QuantedBase(nn.Layer):
    """Shared converted-layer state: per-channel int8 weight + scale
    registered as buffers (so the converted model jit.saves with its
    quantized state) and the PTQ-calibrated activation grid.

    A per-channel activation calibration (PerChannelAbsmaxObserver /
    FakeQuanterChannelWiseAbsMax) is PRESERVED: the scale stays a
    vector broadcast along the observer's channel_axis at quant time.
    A vector scale arriving WITHOUT a channel axis cannot be placed —
    it collapses to the conservative per-tensor max, with a warning
    (silent coarsening was ADVICE r5 #6)."""

    def __init__(self, weight, axis, act_scale, act_channel_axis=None):
        super().__init__()
        qw, ws = quantize_absmax(weight, axis=axis)
        self.register_buffer("qweight", Tensor._wrap(qw))
        self.register_buffer("wscale",
                             Tensor._wrap(jnp.asarray(ws, jnp.float32)))
        self.act_channel_axis = act_channel_axis
        self._act_scalar = None
        self._act_per_channel = False
        if act_scale is None:
            return
        arr = np.asarray(act_scale, np.float32)
        if arr.ndim == 0 or arr.size == 1:
            self._act_scalar = float(arr.reshape(()))
        elif act_channel_axis is None:
            import warnings

            warnings.warn(
                f"per-channel activation scale (shape {arr.shape}) "
                "converted without a channel_axis — collapsing to the "
                "per-tensor max (coarser than calibrated); pass the "
                "observer's channel_axis to keep the vector scale")
            self._act_scalar = float(arr.max())
        else:
            # the buffer is the ONE source of truth for the vector
            # grid (state_dict round-trips it; act_scale reads it)
            self.register_buffer("ascale",
                                 Tensor._wrap(jnp.asarray(arr)))
            self._act_per_channel = True

    @property
    def act_scale(self):
        """Calibrated activation grid: None (uncalibrated), a float
        (per-tensor), or the per-channel vector read from the `ascale`
        buffer (so a loaded state_dict is reflected here too)."""
        if self._act_per_channel:
            return np.asarray(self.ascale._array)
        return self._act_scalar

    def _quant_act(self, x):
        """Round x to the observed int8 activation grid (no-op without
        a calibrated scale; per-channel grid when the observer was
        per-channel)."""
        qmax = 127
        if self._act_per_channel:
            axis = self.act_channel_axis

            def aq_vec(a, s):
                shape = [1] * a.ndim
                shape[axis] = -1
                sv = s.reshape(shape)
                return jnp.clip(jnp.round(a / sv), -qmax - 1, qmax) * sv

            return apply("quant_act_perchannel", aq_vec, x, self.ascale)
        if self._act_scalar is None:
            return x
        s = self._act_scalar

        def aq(a):
            return jnp.clip(jnp.round(a / s), -qmax - 1, qmax) * s

        return apply("quant_act", aq, x)

    def _weight(self):
        return Tensor._wrap(
            dequantize(self.qweight._array, self.wscale._array))


class QuantedLinear(_QuantedBase):
    """Inference-time converted Linear: dequant at the matmul edge."""

    def __init__(self, linear, act_scale=None, act_channel_axis=None):
        super().__init__(linear.weight, axis=1, act_scale=act_scale,
                         act_channel_axis=act_channel_axis)
        self.bias = linear.bias
        self.weight_shape = list(linear.weight.shape)

    def forward(self, x):
        out = self._quant_act(x).matmul(self._weight())
        if self.bias is not None:
            out = out + self.bias
        return out


class QuantedConv2D(_QuantedBase):
    """Inference-time converted Conv2D: per-output-channel int8 weight,
    dequant at the conv edge (reference nn/quant/quantized_conv.py)."""

    def __init__(self, conv, act_scale=None, act_channel_axis=None):
        super().__init__(conv.weight, axis=0, act_scale=act_scale,
                         act_channel_axis=act_channel_axis)
        self.bias = conv.bias
        self._stride = conv._stride
        self._padding = conv._padding
        self._dilation = conv._dilation
        self._groups = conv._groups
        self._data_format = conv._data_format

    def forward(self, x):
        from paddle_tpu.ops import nn_ops

        return nn_ops.conv2d(self._quant_act(x), self._weight(),
                             self.bias, self._stride, self._padding,
                             self._dilation, self._groups,
                             self._data_format)


def _replace_layers(model, predicate, factory):
    for name, child in list(model._sub_layers.items()):
        if predicate(child):
            setattr(model, name, factory(child))
        else:
            _replace_layers(child, predicate, factory)
    return model


class QAT:
    """qat.py:QAT — wrap quantizable layers (Linear + Conv2D) with
    fake-quanters; convert() swaps the trained wrappers for int8
    inference layers (reference QAT.convert)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=True):
        cfg = self.config

        def factory(l):
            if isinstance(l, nn.Conv2D):
                return QATConv2D(l, cfg._make("a"), cfg._make("w"))
            return QATLinear(l, cfg._make("a"), cfg._make("w"))

        return _replace_layers(
            model, lambda l: isinstance(l, (nn.Linear, nn.Conv2D)),
            factory)

    def convert(self, model, inplace=True):
        def factory(l):
            q = l.a_quanter
            act = q.scale() if q is not None and \
                getattr(q, "_absmax", None) is not None else None
            # a per-channel activation quanter's axis rides along so
            # the vector calibration survives conversion
            ax = getattr(q, "channel_axis", None) if q is not None \
                else None
            if isinstance(l, QATConv2D):
                return QuantedConv2D(l.inner, act_scale=act,
                                     act_channel_axis=ax)
            return QuantedLinear(l.inner, act_scale=act,
                                 act_channel_axis=ax)

        return _replace_layers(
            model, lambda l: isinstance(l, (QATLinear, QATConv2D)),
            factory)


class PTQ:
    """ptq.py:PTQ — observe activations, then convert to quantized
    inference layers."""

    class _Observed(nn.Layer):
        def __init__(self, inner, observer):
            super().__init__()
            self.inner = inner
            self.observer = observer

        def forward(self, x):
            if self.observer is not None:
                x = self.observer(x)
            return self.inner(x)

    def __init__(self, config: QuantConfig = None):
        self.config = config or QuantConfig(activation=AbsmaxObserver,
                                            weight=None)

    def quantize(self, model, inplace=True):
        cfg = self.config
        return _replace_layers(
            model, lambda l: isinstance(l, (nn.Linear, nn.Conv2D)),
            lambda l: PTQ._Observed(l, cfg._make("a")))

    def convert(self, model, inplace=True):
        def factory(l):
            act = l.observer.scale() if l.observer else None
            ax = getattr(l.observer, "channel_axis", None) \
                if l.observer else None
            if isinstance(l.inner, nn.Conv2D):
                return QuantedConv2D(l.inner, act_scale=act,
                                     act_channel_axis=ax)
            return QuantedLinear(l.inner, act_scale=act,
                                 act_channel_axis=ax)

        return _replace_layers(
            model, lambda l: isinstance(l, PTQ._Observed), factory)


def convert(model, inplace=True):
    """Module-level convert (reference quantization.convert): swap any
    trained QAT wrappers AND any PTQ-observed layers in `model` for
    int8 inference layers. The result jit.saves — quantized weights and
    scales live in buffers, so the artifact carries the int8 state."""
    QAT(QuantConfig()).convert(model, inplace=inplace)
    PTQ().convert(model, inplace=inplace)
    return model
