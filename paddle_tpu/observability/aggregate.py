"""Distributed metric aggregation: fold per-rank snapshots to job-level.

Every rank in the group calls `aggregate()` (collective contract — same
order on every member, like any ProcessGroup op); each receives the
merged result, so rank 0 can expose job-level numbers on its `/metrics`
endpoint while the others stay silent — serve the merged dict via
`MetricsServer(snapshot_fn=...)`, refreshing it from the job loop (the
scrape path must never trigger the collective itself). The fold rides the eager
collective tier (one device per process on the dp×mp CPU/TPU mesh):

1. the local `MetricsRegistry.snapshot()` is serialized to JSON bytes;
2. payload sizes are MAX-all_reduced so every rank pads to one shape
   (collectives are shape-static);
3. one all_gather moves every rank's padded payload everywhere;
4. `merge_snapshots` folds them on the host — counters and histogram
   buckets sum EXACTLY (fixed explicit bounds, no re-bucketing),
   gauges report min/max/mean.

Registries are host-side state, so the data plane is a gather, not an
in-graph psum — metric cardinality differs per rank (a rank that never
stalled has no stall series) and a fixed-schema reduction would either
drop series or force global schema negotiation every scrape.
"""
from __future__ import annotations

import json

from .metrics import get_registry, merge_snapshots

__all__ = ["aggregate"]


def aggregate(group=None, registry=None):
    """Merge every group member's registry snapshot; returns the merged
    snapshot dict on ALL members. With one participant (or outside a
    distributed context) this degenerates to the local snapshot run
    through the same merge path."""
    reg = registry if registry is not None else get_registry()
    local = reg.snapshot()

    from paddle_tpu.distributed import collective as C

    ranks = C._member_ranks(group)
    if len(ranks) <= 1:
        return merge_snapshots([local])

    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.core.tensor import Tensor

    payload = json.dumps(local, sort_keys=True).encode()
    n = Tensor._wrap(jnp.asarray(np.array([len(payload)], np.int32)))
    C.all_reduce(n, op=C.ReduceOp.MAX, group=group)
    cap = int(np.asarray(n._array)[0])

    # [1 + cap] int32: actual length, then payload bytes, zero-padded
    vec = np.zeros(1 + cap, np.int32)
    vec[0] = len(payload)
    vec[1:1 + len(payload)] = np.frombuffer(payload, np.uint8)
    gathered: list = []
    C.all_gather(gathered, Tensor._wrap(jnp.asarray(vec)), group=group)

    snaps = []
    for t in gathered:
        a = np.asarray(t._array)
        ln = int(a[0])
        snaps.append(json.loads(
            a[1:1 + ln].astype(np.uint8).tobytes().decode()))
    return merge_snapshots(snaps)
