"""Training-step telemetry: step time, tokens/s, grad norm, memory.

The training-side counterpart of the engine's serving metrics. A
`TrainingTelemetry` owns the metric handles; feed it per-step either
explicitly (`observe_step`) or by handing it to `jit.TrainStep(...,
telemetry=...)`, which times each compiled step (blocking on the loss,
so the measured time is device time + dispatch, not dispatch alone —
only paid when telemetry is attached).

Device-memory watermarks come from `device/memory.py` on demand
(`record_memory()` / `memory_every=N`), NOT per step: the live-array
fallback walk costs more than it tells in a hot loop (see that
module's header). NaN/Inf events are counted by `framework/nan_inf.py`
into the default registry whenever FLAGS_check_nan_inf trips.
"""
from __future__ import annotations

import time
from contextlib import contextmanager

from .metrics import LATENCY_BUCKETS, get_registry

__all__ = ["TrainingTelemetry"]


class TrainingTelemetry:
    def __init__(self, registry=None, prefix="train",
                 tokens_per_step=None, memory_every=0):
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self.tokens_per_step = tokens_per_step
        self.memory_every = int(memory_every)
        self._steps = reg.counter(
            f"{prefix}_steps_total", "Optimizer steps completed.")
        self._step_time = reg.histogram(
            f"{prefix}_step_seconds",
            "Wall time of one training step (loss blocked on).",
            buckets=LATENCY_BUCKETS)
        self._tokens = reg.counter(
            f"{prefix}_tokens_total", "Tokens consumed by training.")
        self._tps = reg.gauge(
            f"{prefix}_tokens_per_second",
            "Instantaneous tokens/s of the last observed step.")
        self._grad_norm = reg.gauge(
            f"{prefix}_grad_norm", "Last observed global gradient norm.")
        self._loss = reg.gauge(f"{prefix}_loss", "Last observed loss.")
        self._mem = reg.gauge(
            f"{prefix}_device_memory_bytes",
            "Device memory from device.memory.memory_stats.",
            labelnames=("kind",))

    def observe_step(self, step_time_s, tokens=None, grad_norm=None,
                     loss=None):
        self._steps.inc()
        self._step_time.observe(step_time_s)
        tokens = self.tokens_per_step if tokens is None else tokens
        if tokens:
            self._tokens.inc(tokens)
            if step_time_s > 0:
                self._tps.set(tokens / step_time_s)
        if grad_norm is not None:
            self._grad_norm.set(float(grad_norm))
        if loss is not None:
            self._loss.set(float(loss))
        if self.memory_every and \
                int(self._steps.value) % self.memory_every == 0:
            self.record_memory()

    @contextmanager
    def step(self, tokens=None, grad_norm=None):
        """Time a step body: `with tel.step(tokens=B*S): loss = ...`"""
        t0 = time.perf_counter()
        yield
        self.observe_step(time.perf_counter() - t0, tokens=tokens,
                          grad_norm=grad_norm)

    def record_memory(self, device=None):
        """Sample allocated/peak bytes into gauges (peak is a
        high-water gauge — it never goes down between resets)."""
        from paddle_tpu.device.memory import memory_stats

        stats = memory_stats(device)
        self._mem.labels(kind="allocated").set(stats["allocated_bytes"])
        self._mem.labels(kind="peak").set_max(
            stats["peak_allocated_bytes"])
        return stats
