"""Request-scoped tracing + step-phase timeline — the span half of
serving observability (metrics.py answers "how much / how often"; this
module answers "where did request X's 40 ms go").

Three host-side pieces, shared by the engine and the fleet:

- **TraceRecorder**: a thread-safe bounded ring of Chrome
  trace-event-format spans. Every span carries a `trace_id` (one per
  request, minted at intake and riding the disaggregated handoff
  across replicas) plus its own `span_id`/`parent_id`, so one Perfetto
  timeline shows a request crossing engines. The ring is bounded
  (`capacity` spans, oldest dropped first, drops counted) so
  steady-state serving never grows memory without bound.
- **PhaseTimer**: exclusive-time accounting for the named host phases
  one `engine.step()` decomposes into (`STEP_PHASES`). Nested phases
  PAUSE their parent, so per-phase totals partition the step wall
  exactly — the serial-host tax of ROADMAP item 3 becomes a number
  (`engine_step_host_gap_seconds{phase=…}`) instead of an assertion.
- **FlightRecorder**: a bounded ring of recent request-lifecycle
  events (queued/admit/first_token/stall/finish/handoff/…) — the
  postmortem `drain()`'s leak audit attaches to its exception.

Clock policy: every timestamp is `time.perf_counter_ns() // 1000` —
the SAME monotonic microsecond clock `profiler.RecordEvent` stamps its
spans with, so `export_timeline` can merge a TraceRecorder stream and
the profiler's `_HostEventRecorder` stream onto one coherent timeline
without offset juggling (single-process fleets share the clock;
cross-HOST merges go through `tools/merge_timelines.py --align-start`,
which normalizes each file's epoch).

House invariant: tracing is HOST-SIDE ONLY. Nothing in this module
ever becomes a compiled-program argument, so a tracing-enabled engine
runs byte-identical programs to a disabled one (the `sampling=False`
precedent, held trivially by construction). No jax imports — importing
this module must never initialize a backend.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

__all__ = [
    "STEP_PHASES", "TraceRecorder", "PhaseTimer", "FlightRecorder",
    "new_trace_id", "now_us", "merge_trace_events", "export_timeline",
    "profiler_host_events",
]

#: The named host phases one `engine.step()` decomposes into. Every
#: phase is host work between (or around) compiled dispatches:
#: - schedule:      admission loop, lane scan, growth allocation
#: - prefix_lookup: prefix-cache chain walk at admission
#: - adapter_swap:  adapter-page acquire (incl. host->device swap-in)
#: - draft_propose: speculative drafter proposal (host-side)
#: - dispatch:      building host args + issuing a compiled step
#: - device_wait:   blocking on device results (block_until_ready
#:                  discipline — the only phase that is device time)
#: - accept_walk:   greedy draft-acceptance walk over verify output
#: - sample_walk:   rejection-sampling acceptance walk (sampled lanes)
#: - cow:           copy-on-write block promotion
#: - finish:        token emission, TTFT/TPOT accounting, retirement
STEP_PHASES = ("schedule", "prefix_lookup", "adapter_swap",
               "draft_propose", "dispatch", "device_wait",
               "accept_walk", "sample_walk", "cow", "finish")

_trace_seq = itertools.count(1)


def now_us():
    """Monotonic microseconds — the shared span clock (see module
    docstring for the cross-stream merge policy)."""
    return time.perf_counter_ns() // 1000


def new_trace_id():
    """Process-unique request trace id. Deliberately NOT random: the
    pid prefix keeps ids unique across processes (multi-host fleets)
    while the counter keeps single-process test traces deterministic."""
    return f"{os.getpid():x}-{next(_trace_seq):x}"


class TraceRecorder:
    """Thread-safe bounded ring of Chrome trace-event spans.

    Events are plain dicts in the trace-event JSON schema ("X" duration
    spans, "i" instants), timestamped by `now_us()`. The ring holds the
    newest `capacity` events; `dropped` counts evictions so a truncated
    export is visible, never silent.
    """

    def __init__(self, capacity=4096, process_name="engine"):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.process_name = process_name
        self._events = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._span_seq = itertools.count(1)
        self.total_recorded = 0

    @property
    def dropped(self):
        """Events evicted by the ring bound (recorded - retained)."""
        with self._lock:
            return self.total_recorded - len(self._events)

    def new_span_id(self):
        return next(self._span_seq)

    def _push(self, ev):
        with self._lock:
            self._events.append(ev)
            self.total_recorded += 1

    def add_span(self, name, start_us, end_us, *, trace_id=None,
                 parent_id=None, tid=0, cat="engine", args=None):
        """Record one completed span; returns its span id (usable as
        another span's `parent_id`)."""
        sid = self.new_span_id()
        a = {"span_id": sid}
        if trace_id is not None:
            a["trace_id"] = trace_id
        if parent_id is not None:
            a["parent_id"] = parent_id
        if args:
            a.update(args)
        self._push({"name": name, "ph": "X", "ts": int(start_us),
                    "dur": max(int(end_us) - int(start_us), 0),
                    "pid": os.getpid(), "tid": int(tid), "cat": cat,
                    "args": a})
        return sid

    def add_instant(self, name, ts_us=None, *, trace_id=None, tid=0,
                    cat="engine", args=None):
        """Record a zero-duration marker (finish reasons, sheds,
        first-token ticks)."""
        a = {}
        if trace_id is not None:
            a["trace_id"] = trace_id
        if args:
            a.update(args)
        self._push({"name": name, "ph": "i", "s": "t",
                    "ts": int(now_us() if ts_us is None else ts_us),
                    "pid": os.getpid(), "tid": int(tid), "cat": cat,
                    "args": a})

    @contextmanager
    def span(self, name, *, trace_id=None, parent_id=None, tid=0,
             cat="engine", args=None):
        t0 = now_us()
        try:
            yield
        finally:
            self.add_span(name, t0, now_us(), trace_id=trace_id,
                          parent_id=parent_id, tid=tid, cat=cat,
                          args=args)

    def snapshot(self):
        """Non-destructive copy of the retained events, oldest first."""
        with self._lock:
            return [dict(e) for e in self._events]

    def clear(self):
        with self._lock:
            self._events.clear()
            self.total_recorded = 0


class PhaseTimer:
    """Exclusive-time phase accounting for one scheduler iteration.

    `phase(name)` is a reentrant-by-stack context manager: entering a
    nested phase PAUSES the enclosing one, so `totals()` values are
    disjoint and sum to (at most) the step's wall time — the property
    that makes `engine_step_device_fraction` = device_wait / wall a
    real fraction instead of double-counting nested sections.

    Thread-confined: each thread owns its own stack AND accumulator
    (the async engine core runs drafter proposals on a helper thread
    while the step thread is inside its own phases — a phase recorded
    off the step thread must neither pause the step thread's active
    phase nor fold its overlapped seconds into the step thread's
    totals, or phase sums would exceed step wall time and
    `engine_step_device_fraction` would stop being a fraction).
    `reset()` and `totals()` operate on the calling thread's clock
    only; no locks needed because no state is shared.
    """

    def __init__(self):
        self._tls = threading.local()

    def _state(self):
        tls = self._tls
        if not hasattr(tls, "acc"):
            tls.acc = {}
            tls.stack = []             # [name, slice_start] frames
        return tls.acc, tls.stack

    def reset(self):
        acc, stack = self._state()
        self._tls.acc = {}
        stack.clear()
        return acc

    @contextmanager
    def phase(self, name):
        acc, stack = self._state()
        now = time.perf_counter()
        if stack:                      # pause the enclosing phase
            outer = stack[-1]
            acc[outer[0]] = acc.get(outer[0], 0.0) + now - outer[1]
        stack.append([name, now])
        try:
            yield
        finally:
            acc, stack = self._state()
            frame = stack.pop()
            now = time.perf_counter()
            acc[frame[0]] = acc.get(frame[0], 0.0) + now - frame[1]
            if stack:                  # resume the enclosing phase
                stack[-1][1] = now

    def totals(self):
        """phase -> accumulated exclusive seconds since last reset,
        for the CALLING thread's clock."""
        return dict(self._state()[0])


class FlightRecorder:
    """Bounded ring of recent request-lifecycle events — the engine's
    black box. Always on (a handful of dict appends per request, far
    off any hot path), bounded so steady-state serving never grows it,
    and formatted into `drain()`'s leak-audit exception so a failed
    audit arrives WITH the recent history that explains it."""

    def __init__(self, capacity=256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._events = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.total_recorded = 0

    def record(self, event, req_id=None, **detail):
        ev = {"t_us": now_us(), "event": event}
        if req_id is not None:
            ev["req_id"] = req_id
        if detail:
            ev.update(detail)
        with self._lock:
            self._events.append(ev)
            self.total_recorded += 1

    def dump(self):
        """Retained events, oldest first (JSON-able dicts)."""
        with self._lock:
            return [dict(e) for e in self._events]

    def format(self, limit=None):
        """Human-readable tail for exception messages."""
        rows = self.dump()
        if limit is not None:
            rows = rows[-limit:]
        head = (f"flight recorder ({len(rows)} of "
                f"{self.total_recorded} events, newest last):")
        lines = [head]
        for e in rows:
            extra = " ".join(f"{k}={e[k]}" for k in e
                             if k not in ("t_us", "event", "req_id"))
            rid = f" req={e['req_id']!r}" if "req_id" in e else ""
            lines.append(f"  [{e['t_us']}us] {e['event']}{rid}"
                         + (f" {extra}" if extra else ""))
        return "\n".join(lines)


def profiler_host_events():
    """Non-destructive peek at the profiler's `_HostEventRecorder`
    stream (the `engine.step`/`engine.prefill`/`engine.decode`/
    `engine.cow` spans `RecordEvent` emits while a Profiler records).
    Lazy import: the profiler package is stdlib-only too, but tracing
    must stay importable standalone."""
    from paddle_tpu.profiler.profiler import _recorder

    return _recorder.peek()


def merge_trace_events(groups):
    """Merge named event streams onto one timeline: `groups` is an
    iterable of (process_name, events). Each group is re-pidded to a
    stable small integer (1, 2, …) with a `process_name` metadata
    event, so Perfetto renders one track group per engine/replica/
    profiler stream — events share the monotonic clock (module
    docstring), so no timestamp shifting happens here."""
    out = []
    for pid, (pname, events) in enumerate(groups, start=1):
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "tid": 0, "args": {"name": pname}})
        for ev in events:
            e = dict(ev)
            e["pid"] = pid
            out.append(e)
    return out


def export_timeline(path, groups):
    """Write merged `groups` (see `merge_trace_events`) as one Chrome
    trace-event / Perfetto JSON file. Returns the event count."""
    events = merge_trace_events(groups)
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return len(events)
