"""Loopback-only HTTP `/metrics` endpoint.

Pull-based exposition on an ephemeral port (the Prometheus model): the
scraper initiates, the process never pushes. Deliberately restricted to
loopback binds — the registry can carry prompt lengths, pool sizes and
rank topology, and the FL/elastic tiers already established the rule
that unauthenticated plaintext services in this repo never leave the
host (DESIGN_DECISIONS.md). A production scrape path fronts this with
the pod's service mesh, not a 0.0.0.0 bind.
"""
from __future__ import annotations

import json
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .exposition import CONTENT_TYPE, render_prometheus
from .metrics import json_sanitize

__all__ = ["MetricsServer"]

_LOOPBACK = ("127.0.0.1", "localhost", "::1")


class _V6Server(ThreadingHTTPServer):
    address_family = socket.AF_INET6


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):
        snapshot = self.server._snapshot  # type: ignore[attr-defined]
        path = self.path.partition("?")[0]   # scrape params are legal
        if path in ("/metrics", "/"):
            body = render_prometheus(snapshot()).encode()
            ctype = CONTENT_TYPE
        elif path == "/metrics.json":
            body = json.dumps(json_sanitize(snapshot()),
                              sort_keys=True).encode()
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):          # scrapes are not stdout news
        pass


class MetricsServer:
    """Serve a registry's exposition on loopback.

        srv = MetricsServer(registry)      # ephemeral port, started
        requests.get(srv.url)              # text exposition
        requests.get(srv.url + '.json')    # JSON snapshot
        srv.close()

    `snapshot_fn` overrides the data source — e.g. rank 0 serving a
    job-level snapshot refreshed by periodic `aggregate()` calls:

        merged = {}                        # refreshed by the job loop:
        ...  merged.update(aggregate())    # (collective — call it from
        srv = MetricsServer(snapshot_fn=lambda: merged)   # the loop,
                                           # NEVER from the scrape path)
    """

    def __init__(self, registry=None, host="127.0.0.1", port=0,
                 snapshot_fn=None):
        if host not in _LOOPBACK:
            raise ValueError(
                f"metrics endpoint is loopback-only (got {host!r}); "
                "front it with a proxy to expose it off-host")
        if snapshot_fn is None:
            if registry is None:
                from .metrics import get_registry

                registry = get_registry()
            snapshot_fn = registry.snapshot
        self.registry = registry
        cls = _V6Server if ":" in host else ThreadingHTTPServer
        self._srv = cls((host, port), _Handler)
        self._srv.daemon_threads = True
        self._srv._snapshot = snapshot_fn  # type: ignore[attr-defined]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    @property
    def url(self) -> str:
        host = self._srv.server_address[0]
        if ":" in host:
            host = f"[{host}]"               # bracketed IPv6 authority
        return f"http://{host}:{self.port}/metrics"

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
