"""Thread-safe metrics registry: labeled Counter / Gauge / Histogram.

The *metrics* half of the observability story (the profiler reproduces
the reference's span/trace half): monotonically increasing counters,
point-in-time gauges, and fixed-bucket histograms that a scrape
endpoint (`observability.server`), a bench harness (`bench_ops.py`), or
a cross-rank fold (`observability.aggregate`) can read continuously —
the serving-telemetry style of Orca/vLLM (TTFT, per-output-token
latency, KV-pool utilization).

Design constraints this module enforces:

- histograms use FIXED EXPLICIT bucket bounds declared at creation, so
  a cross-rank merge is an exact elementwise sum of counts — no
  re-bucketing, no approximation (see DESIGN_DECISIONS.md);
- no jax / device imports at module level: importing observability must
  never initialize a backend (a metrics scrape thread on a serving host
  must not race device init);
- everything is guarded by one registry lock — increments are a dict
  update + float add, far off any hot path's critical section.
"""
from __future__ import annotations

import json
import math
import re
import threading

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "LATENCY_BUCKETS", "DEFAULT_BUCKETS",
    "merge_snapshots", "label_snapshot", "quantile_from_buckets",
    "series_total",
]

# latency buckets (seconds): sub-ms decode steps through multi-second
# prefill; shared by every latency histogram so cross-metric and
# cross-rank comparisons line up bucket-for-bucket
LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
DEFAULT_BUCKETS = LATENCY_BUCKETS

_METRIC_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class _Family:
    """One named metric family: a set of label-keyed series sharing a
    type and help string. Child handles are cached per label tuple so
    hot-path `.labels(...)` is a dict hit."""

    kind = None

    def __init__(self, registry, name, help, labelnames):
        if not _METRIC_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
            if ln == "le":
                raise ValueError("label name 'le' is reserved for "
                                 "histogram buckets")
        self._registry = registry
        self._lock = registry._lock
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._series = {}            # label-value tuple -> child

    def labels(self, *values, **kw):
        if kw:
            if values:
                raise ValueError("pass labels positionally or by "
                                 "keyword, not both")
            try:
                values = tuple(kw[ln] for ln in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: missing label {e.args[0]!r} "
                    f"(labelnames={self.labelnames})") from None
            if len(kw) != len(self.labelnames):
                extra = set(kw) - set(self.labelnames)
                raise ValueError(f"{self.name}: unknown labels {extra}")
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes {len(self.labelnames)} label "
                f"value(s) {self.labelnames}, got {len(values)}")
        with self._lock:
            child = self._series.get(values)
            if child is None:
                child = self._series[values] = self._new_child()
            return child

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; call "
                ".labels(...) first")
        return self.labels()

    def _snapshot_series(self):
        # children snapped while HOLDING the lock: a histogram observe
        # mutates counts/sum/count as three writes, and a concurrent
        # scrape must not see them torn (the lock is an RLock)
        with self._lock:
            out = []
            for values, child in sorted(self._series.items()):
                entry = {"labels": dict(zip(self.labelnames, values))}
                entry.update(child._snap())
                out.append(entry)
        return out

    def _reset(self):
        # zero children IN PLACE: callers hold .labels() handles for
        # hot-path speed, and clearing the dict would orphan them (their
        # increments would silently stop appearing in snapshots)
        with self._lock:
            for child in self._series.values():
                child._zero()


class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self, lock):
        self._value = 0.0
        self._lock = lock

    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    def _zero(self):
        self._value = 0.0            # guarded-by: _lock

    @property
    def value(self):
        return self._value

    def _snap(self):
        return {"value": self._value}


class Counter(_Family):
    """Monotonic counter family (prometheus `counter`)."""

    kind = "counter"

    def _new_child(self):
        return _CounterChild(self._lock)

    def inc(self, amount=1.0):
        self._default().inc(amount)

    @property
    def value(self):
        return self._default().value


class _GaugeChild:
    __slots__ = ("_value", "_lock")

    def __init__(self, lock):
        self._value = 0.0
        self._lock = lock

    def set(self, value):
        with self._lock:
            self._value = float(value)

    def inc(self, amount=1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount=1.0):
        self.inc(-amount)

    def set_max(self, value):
        """High-water-mark update: keep the larger of current/new."""
        with self._lock:
            if value > self._value:
                self._value = float(value)

    def _zero(self):
        self._value = 0.0            # guarded-by: _lock

    @property
    def value(self):
        return self._value

    def _snap(self):
        return {"value": self._value}


class Gauge(_Family):
    """Point-in-time gauge family (prometheus `gauge`)."""

    kind = "gauge"

    def _new_child(self):
        return _GaugeChild(self._lock)

    def set(self, value):
        self._default().set(value)

    def inc(self, amount=1.0):
        self._default().inc(amount)

    def dec(self, amount=1.0):
        self._default().dec(amount)

    def set_max(self, value):
        self._default().set_max(value)

    @property
    def value(self):
        return self._default().value


class _HistogramChild:
    __slots__ = ("_counts", "_sum", "_count", "_bounds", "_lock")

    def __init__(self, bounds, lock):
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)   # last = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._lock = lock

    def observe(self, value):
        value = float(value)
        i = len(self._bounds)
        for j, b in enumerate(self._bounds):
            if value <= b:
                i = j
                break
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def _zero(self):
        self._counts = [0] * len(self._counts)   # guarded-by: _lock
        self._sum = 0.0                          # guarded-by: _lock
        self._count = 0                          # guarded-by: _lock

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def _snap(self):
        return {"counts": list(self._counts), "sum": self._sum,
                "count": self._count}


class Histogram(_Family):
    """Fixed-explicit-bucket histogram family (prometheus `histogram`).

    `buckets` are inclusive upper bounds; an implicit +Inf bucket
    catches the overflow. Bounds are part of the family identity:
    cross-rank merges require identical bounds and are then EXACT."""

    kind = "histogram"

    def __init__(self, registry, name, help, labelnames,
                 buckets=DEFAULT_BUCKETS):
        super().__init__(registry, name, help, labelnames)
        bounds = tuple(float(b) for b in buckets)
        if list(bounds) != sorted(set(bounds)):
            raise ValueError("bucket bounds must be strictly increasing")
        if bounds and math.isinf(bounds[-1]):
            bounds = bounds[:-1]         # +Inf is implicit
        if not bounds:                   # post-strip: (inf,) is empty too
            raise ValueError("histogram needs at least one finite "
                             "bucket bound")
        self.buckets = bounds

    def _new_child(self):
        return _HistogramChild(self.buckets, self._lock)

    def observe(self, value):
        self._default().observe(value)

    @property
    def count(self):
        return self._default().count

    @property
    def sum(self):
        return self._default().sum


class MetricsRegistry:
    """Named collection of metric families.

        reg = MetricsRegistry()
        reqs = reg.counter("requests_total", "Requests seen.",
                           labelnames=("verb",))
        reqs.labels(verb="GET").inc()
        reg.snapshot()            # JSON-able dict
        reg.render_prometheus()   # text exposition (format 0.0.4)

    Re-requesting an existing name returns the same family when the
    declaration matches, and raises when it conflicts — instrumentation
    can therefore be declared idempotently at call sites."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or \
                        fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames}, requested "
                        f"{cls.kind}{tuple(labelnames)}")
                if kw.get("buckets") is not None:
                    # normalize like Histogram.__init__ (trailing +Inf
                    # is implicit) so identical declarations stay
                    # idempotent
                    req = tuple(float(b) for b in kw["buckets"])
                    if req and math.isinf(req[-1]):
                        req = req[:-1]
                    if fam.buckets != req:
                        raise ValueError(
                            f"histogram {name!r} already registered "
                            f"with buckets {fam.buckets}")
                return fam
            fam = cls(self, name, help, labelnames, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name, help="", labelnames=()):
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_BUCKETS):
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._families.get(name)

    def reset(self):
        """Zero every series (families stay registered) — bench harness
        use: drop warmup observations before the measured window."""
        with self._lock:
            for fam in self._families.values():
                fam._reset()

    # -- snapshots ---------------------------------------------------------
    def snapshot(self):
        """JSON-able view of every family: exact values, exact bucket
        counts — the wire format `aggregate()` folds across ranks."""
        with self._lock:
            fams = list(self._families.items())
        out = {}
        for name, fam in fams:
            entry = {"type": fam.kind, "help": fam.help,
                     "labelnames": list(fam.labelnames),
                     "series": fam._snapshot_series()}
            if fam.kind == "histogram":
                entry["buckets"] = list(fam.buckets)
            out[name] = entry
        return out

    def snapshot_json(self):
        """Strictly-valid JSON (non-finite floats stringified): Python's
        default would emit bare NaN/Infinity tokens that jq/JS parsers
        reject wholesale."""
        return json.dumps(json_sanitize(self.snapshot()),
                          sort_keys=True)

    def render_prometheus(self):
        from .exposition import render_prometheus

        return render_prometheus(self.snapshot())


# process-wide default registry: framework-internal instrumentation
# (nan/inf events, training telemetry) lands here unless told otherwise
_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default_registry


def json_sanitize(obj):
    """Recursively replace non-finite floats with their string names so
    the result serializes to STRICT JSON. Used at external boundaries
    (snapshot_json, /metrics.json); the cross-rank aggregate wire stays
    raw (Python↔Python, tolerant loads, values must merge exactly)."""
    if isinstance(obj, float):
        if math.isnan(obj):
            return "NaN"
        if math.isinf(obj):
            return "Infinity" if obj > 0 else "-Infinity"
        return obj
    if isinstance(obj, dict):
        return {k: json_sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [json_sanitize(v) for v in obj]
    return obj


# ---------------------------------------------------------------------------
# snapshot algebra (host-side; aggregate() runs this after the gather)
# ---------------------------------------------------------------------------

def _series_key(entry):
    return tuple(sorted(entry["labels"].items()))


def label_snapshot(snap, **extra):
    """Copy of a `MetricsRegistry.snapshot()` with `extra` labels
    stamped onto every series (and appended to each family's
    labelnames). The host-side relabeling half of fleet-level folding:
    each replica engine keeps its own registry for counter exactness,
    the fleet stamps `replica=<id>` here and folds the stamped
    snapshots through `merge_snapshots` — identical label sets sum
    exactly, the replica label keeps per-replica series side-by-side
    (same semantics as the shard-labeled pool gauges). Raises on a
    label-name collision instead of silently shadowing a real label."""
    out = {}
    for name, fam in snap.items():
        clash = set(extra) & set(fam["labelnames"])
        if clash:
            raise ValueError(
                f"metric {name!r} already carries label(s) "
                f"{sorted(clash)} — relabeling would shadow them")
        f = {"type": fam["type"], "help": fam["help"],
             "labelnames": list(fam["labelnames"]) + sorted(extra),
             "series": [dict(entry, labels=dict(entry["labels"],
                                                **extra))
                        for entry in fam["series"]]}
        if fam["type"] == "histogram":
            f["buckets"] = list(fam["buckets"])
        out[name] = f
    return out


def merge_snapshots(snaps):
    """Fold per-rank `MetricsRegistry.snapshot()` dicts into one
    job-level snapshot: counters and histogram buckets/sum/count are
    summed EXACTLY per labeled series; gauges report min/max/mean (and
    carry the mean as `value`). Histogram bucket bounds must agree
    across ranks — fixed explicit buckets make the merge lossless."""
    merged = {}
    for snap in snaps:
        for name, fam in snap.items():
            m = merged.get(name)
            if m is None:
                m = merged[name] = {
                    "type": fam["type"], "help": fam["help"],
                    "labelnames": list(fam["labelnames"]),
                    "series": {},
                }
                if fam["type"] == "histogram":
                    m["buckets"] = list(fam["buckets"])
            if m["type"] != fam["type"]:
                raise ValueError(
                    f"metric {name!r}: type mismatch across ranks "
                    f"({m['type']} vs {fam['type']})")
            if fam["type"] == "histogram" and \
                    list(fam["buckets"]) != m["buckets"]:
                raise ValueError(
                    f"histogram {name!r}: bucket bounds differ across "
                    "ranks — declare fixed explicit buckets")
            for entry in fam["series"]:
                key = _series_key(entry)
                tgt = m["series"].get(key)
                if fam["type"] == "counter":
                    if tgt is None:
                        m["series"][key] = dict(entry)
                    else:
                        tgt["value"] += entry["value"]
                elif fam["type"] == "gauge":
                    if tgt is None:
                        v = entry["value"]
                        m["series"][key] = {
                            "labels": dict(entry["labels"]), "value": v,
                            "min": v, "max": v, "mean": v, "ranks": 1}
                    else:
                        tgt["min"] = min(tgt["min"], entry["value"])
                        tgt["max"] = max(tgt["max"], entry["value"])
                        n = tgt["ranks"] + 1
                        tgt["mean"] += (entry["value"] - tgt["mean"]) / n
                        tgt["ranks"] = n
                        tgt["value"] = tgt["mean"]
                else:                      # histogram
                    if tgt is None:
                        m["series"][key] = {
                            "labels": dict(entry["labels"]),
                            "counts": list(entry["counts"]),
                            "sum": entry["sum"],
                            "count": entry["count"]}
                    else:
                        if len(tgt["counts"]) != len(entry["counts"]):
                            raise ValueError(
                                f"histogram {name!r}: bucket count "
                                "mismatch across ranks")
                        tgt["counts"] = [a + b for a, b in
                                         zip(tgt["counts"],
                                             entry["counts"])]
                        tgt["sum"] += entry["sum"]
                        tgt["count"] += entry["count"]
    for fam in merged.values():
        fam["series"] = [fam["series"][k] for k in sorted(fam["series"])]
    return merged


def quantile_from_buckets(bounds, counts, q):
    """Approximate quantile q in [0, 1] from fixed-bucket counts by
    linear interpolation inside the containing bucket (the prometheus
    histogram_quantile rule). None on an empty histogram; observations
    past the last bound clamp to it."""
    total = sum(counts)
    if total == 0:
        return None
    target = q * total
    cum = 0
    lo = 0.0
    for bound, c in zip(bounds, counts):
        if cum + c >= target and c > 0:
            frac = (target - cum) / c
            return lo + (bound - lo) * frac
        cum += c
        lo = bound
    return float(bounds[-1])


def series_total(snapshot, name):
    """Sum of a counter family's series values (all labels) in a
    snapshot; 0.0 when the family is absent."""
    fam = snapshot.get(name)
    if fam is None:
        return 0.0
    return float(sum(s["value"] for s in fam["series"]))
