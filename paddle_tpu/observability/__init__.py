"""Unified metrics & telemetry — the counters/gauges/histograms half of
observability (the profiler package holds the span/trace half).

    from paddle_tpu import observability as obs

    reg = obs.get_registry()                    # process-default registry
    reqs = reg.counter("requests_total", "...", labelnames=("verb",))
    reqs.labels(verb="GET").inc()

    print(reg.render_prometheus())              # text exposition
    srv = obs.MetricsServer(reg)                # loopback /metrics
    merged = obs.aggregate()                    # fold across ranks

Importing this package has no JAX side effects (no backend/device
init); the distributed fold and memory sampling import lazily.
"""
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    LATENCY_BUCKETS,
    MetricsRegistry,
    get_registry,
    label_snapshot,
    merge_snapshots,
    quantile_from_buckets,
    series_total,
)
from .aggregate import aggregate
from .exposition import parse_prometheus, render_prometheus
from .server import MetricsServer
from .tracing import (
    STEP_PHASES,
    FlightRecorder,
    PhaseTimer,
    TraceRecorder,
    export_timeline,
    merge_trace_events,
    new_trace_id,
)
from .training import TrainingTelemetry

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "get_registry",
    "LATENCY_BUCKETS", "merge_snapshots", "label_snapshot",
    "quantile_from_buckets", "series_total", "aggregate",
    "render_prometheus", "parse_prometheus", "MetricsServer",
    "TrainingTelemetry",
    "TraceRecorder", "PhaseTimer", "FlightRecorder", "STEP_PHASES",
    "new_trace_id", "merge_trace_events", "export_timeline",
]
