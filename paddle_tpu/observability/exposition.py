"""Prometheus text exposition (format 0.0.4) + a strict parser.

`render_prometheus` turns a `MetricsRegistry.snapshot()` into the
`# HELP` / `# TYPE` / sample-line format any Prometheus-compatible
scraper ingests; `parse_prometheus` reads it back into sample dicts.
The parser exists so CI can prove the round-trip is lossless (golden
test) — it is NOT a general scraper (no timestamps, no exemplars,
no OpenMetrics extensions).
"""
from __future__ import annotations

import math
import re

__all__ = ["render_prometheus", "parse_prometheus", "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(s):
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label(s):
    return s.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _fmt(v):
    """Integral values print as integers (bucket counts must not grow
    '.0' suffixes), everything else as shortest-repr float. NaN renders
    as the literal the text format defines — a diverged-loss gauge must
    not take the whole scrape down."""
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labelstr(labels, extra=()):
    items = list(labels.items()) + list(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in items)
    return "{" + body + "}"


def render_prometheus(snapshot):
    lines = []
    for name in sorted(snapshot):
        fam = snapshot[name]
        if fam["help"]:
            lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for s in fam["series"]:
            if fam["type"] in ("counter", "gauge"):
                lines.append(
                    f"{name}{_labelstr(s['labels'])} {_fmt(s['value'])}")
            else:                                       # histogram
                cum = 0
                for bound, c in zip(fam["buckets"], s["counts"]):
                    cum += c
                    lines.append(
                        f"{name}_bucket"
                        f"{_labelstr(s['labels'], [('le', _fmt(bound))])}"
                        f" {_fmt(cum)}")
                cum += s["counts"][-1]
                lines.append(
                    f"{name}_bucket"
                    f"{_labelstr(s['labels'], [('le', '+Inf')])}"
                    f" {_fmt(cum)}")
                lines.append(f"{name}_sum{_labelstr(s['labels'])}"
                             f" {_fmt(s['sum'])}")
                lines.append(f"{name}_count{_labelstr(s['labels'])}"
                             f" {_fmt(s['count'])}")
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>\S+)$")
_LABEL_PAIR_RE = re.compile(
    r'\s*(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"\s*(?:,|$)')


_UNESCAPE_RE = re.compile(r"\\(.)")
_UNESCAPES = {"n": "\n", '"': '"', "\\": "\\"}


def _unescape(s):
    # single left-to-right pass: sequential str.replace would corrupt a
    # literal backslash followed by 'n' (r'\\n' -> '\' + newline)
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPES.get(m.group(1), "\\" + m.group(1)), s)


def _parse_value(s):
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    return float(s)


def parse_prometheus(text):
    """text -> {"types": {name: type}, "help": {name: help},
    "samples": [(name, {label: value}, float)]}. Raises ValueError on a
    malformed line (the golden test's round-trip contract)."""
    types, helps, samples = {}, {}, []
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name, _, h = line[len("# HELP "):].partition(" ")
            helps[name] = _unescape(h)
            continue
        if line.startswith("# TYPE "):
            name, _, t = line[len("# TYPE "):].partition(" ")
            types[name] = t
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        labels = {}
        raw = m.group("labels")
        if raw:
            pos = 0
            while pos < len(raw):
                lm = _LABEL_PAIR_RE.match(raw, pos)
                if not lm:
                    raise ValueError(
                        f"line {lineno}: malformed labels {raw!r}")
                labels[lm.group("k")] = _unescape(lm.group("v"))
                pos = lm.end()
        samples.append((m.group("name"), labels,
                        _parse_value(m.group("value"))))
    return {"types": types, "help": helps, "samples": samples}
