"""Reduction & search ops — analogs of reduce_* kernels
(paddle/phi/kernels/funcs/reduce_*) and python/paddle/tensor/{math,search,stat}.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor

from .dispatch import apply, apply_nograd, as_tensor

__all__ = [
    "sum", "mean", "max", "min", "prod", "std", "var", "median",
    "argmax", "argmin", "argsort", "sort", "topk", "all", "any",
    "cumsum", "cumprod", "logsumexp", "amax", "amin", "count_nonzero",
    "nansum", "nanmean", "kthvalue", "mode", "unique", "nonzero",
    "quantile", "bincount", "nanmedian", "trapezoid",
]


def _axes(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, (int, np.integer)):
        return int(axis)
    return tuple(int(a) for a in axis)


def _reduce(name, fn, grad_ok=True):
    def op(x, axis=None, keepdim=False):
        x = as_tensor(x)
        ax = _axes(axis, x.ndim)
        f = lambda a: fn(a, axis=ax, keepdims=keepdim)
        return apply(name, f, x) if grad_ok else apply_nograd(name, f, x)

    op.__name__ = name
    return op


sum = _reduce("sum", jnp.sum)
mean = _reduce("mean", jnp.mean)
max = _reduce("max", jnp.max)
min = _reduce("min", jnp.min)
amax = _reduce("amax", jnp.max)
amin = _reduce("amin", jnp.min)
prod = _reduce("prod", jnp.prod)
nansum = _reduce("nansum", jnp.nansum)
nanmean = _reduce("nanmean", jnp.nanmean)
logsumexp = _reduce("logsumexp", lambda a, axis, keepdims: jnp.log(
    jnp.sum(jnp.exp(a - jnp.max(a, axis=axis, keepdims=True)), axis=axis, keepdims=keepdims)
) + (jnp.max(a, axis=axis, keepdims=keepdims)))


def std(x, axis=None, unbiased=True, keepdim=False):
    x = as_tensor(x)
    ax = _axes(axis, x.ndim)
    ddof = 1 if unbiased else 0
    return apply("std", lambda a: jnp.std(a, axis=ax, ddof=ddof, keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False):
    x = as_tensor(x)
    ax = _axes(axis, x.ndim)
    ddof = 1 if unbiased else 0
    return apply("var", lambda a: jnp.var(a, axis=ax, ddof=ddof, keepdims=keepdim), x)


def median(x, axis=None, keepdim=False):
    x = as_tensor(x)
    ax = _axes(axis, x.ndim)
    return apply("median", lambda a: jnp.median(a, axis=ax, keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False):
    x = as_tensor(x)
    ax = _axes(axis, x.ndim)
    return apply("quantile", lambda a: jnp.quantile(a, q, axis=ax, keepdims=keepdim), x)


def argmax(x, axis=None, keepdim=False, dtype="int64"):
    from paddle_tpu.core import dtype as dtypes

    x = as_tensor(x)
    ax = _axes(axis, x.ndim)

    def fn(a):
        r = jnp.argmax(a, axis=ax, keepdims=keepdim if ax is not None else False)
        return r.astype(dtypes.to_jax(dtype))

    return apply_nograd("argmax", fn, x)


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    from paddle_tpu.core import dtype as dtypes

    x = as_tensor(x)
    ax = _axes(axis, x.ndim)

    def fn(a):
        r = jnp.argmin(a, axis=ax, keepdims=keepdim if ax is not None else False)
        return r.astype(dtypes.to_jax(dtype))

    return apply_nograd("argmin", fn, x)


def argsort(x, axis=-1, descending=False):
    x = as_tensor(x)

    def fn(a):
        idx = jnp.argsort(a, axis=axis)
        return jnp.flip(idx, axis=axis) if descending else idx

    return apply_nograd("argsort", fn, x)


def sort(x, axis=-1, descending=False):
    x = as_tensor(x)

    def fn(a):
        s = jnp.sort(a, axis=axis)
        return jnp.flip(s, axis=axis) if descending else s

    return apply("sort", fn, x)


def topk(x, k, axis=-1, largest=True, sorted=True):
    import jax.lax

    x = as_tensor(x)
    k = int(k)
    axis_ = axis

    def fn(a):
        a2 = jnp.moveaxis(a, axis_, -1)
        if largest:
            v, i = jax.lax.top_k(a2, k)
        else:
            v, i = jax.lax.top_k(-a2, k)
            v = -v
        return jnp.moveaxis(v, -1, axis_), jnp.moveaxis(i, -1, axis_).astype(jnp.int32)

    values, indices = apply("topk", fn, x)
    return values, indices


def all(x, axis=None, keepdim=False):
    x = as_tensor(x)
    ax = _axes(axis, x.ndim)
    return apply_nograd("all", lambda a: jnp.all(a, axis=ax, keepdims=keepdim), x)


def any(x, axis=None, keepdim=False):
    x = as_tensor(x)
    ax = _axes(axis, x.ndim)
    return apply_nograd("any", lambda a: jnp.any(a, axis=ax, keepdims=keepdim), x)


def cumsum(x, axis=None):
    x = as_tensor(x)
    if axis is None:
        return apply("cumsum", lambda a: jnp.cumsum(a.reshape(-1)), x)
    return apply("cumsum", lambda a: jnp.cumsum(a, axis=int(axis)), x)


def cumprod(x, dim=None):
    x = as_tensor(x)
    return apply("cumprod", lambda a: jnp.cumprod(a, axis=dim), x)


def count_nonzero(x, axis=None, keepdim=False):
    x = as_tensor(x)
    ax = _axes(axis, x.ndim)
    return apply_nograd(
        "count_nonzero", lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim), x
    )


def kthvalue(x, k, axis=-1, keepdim=False):
    x = as_tensor(x)

    def fn(a):
        s = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis)
        v = jnp.take(s, k - 1, axis=axis)
        ix = jnp.take(i, k - 1, axis=axis)
        if keepdim:
            v = jnp.expand_dims(v, axis)
            ix = jnp.expand_dims(ix, axis)
        return v, ix.astype(jnp.int32)

    return apply("kthvalue", fn, x)


def mode(x, axis=-1, keepdim=False):
    x = as_tensor(x)

    def fn(a):
        # mode via sort: the most frequent value ends a maximal run
        s = jnp.sort(a, axis=axis)
        same = jnp.concatenate(
            [jnp.zeros_like(jnp.take(s, jnp.array([0]), axis=axis), dtype=jnp.int32),
             (jnp.diff(s, axis=axis) == 0).astype(jnp.int32)], axis=axis)
        run = jnp.cumsum(same, axis=axis) - jnp.cumsum(
            jnp.where(same == 0, jnp.cumsum(same, axis=axis), 0), axis=axis
        )
        best = jnp.argmax(run, axis=axis, keepdims=True)
        v = jnp.take_along_axis(s, best, axis=axis)
        if not keepdim:
            v = jnp.squeeze(v, axis)
        return v

    return apply_nograd("mode", fn, x)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    # dynamic output shape -> host-side eager only
    x = as_tensor(x)
    res = np.unique(
        np.asarray(x._array),
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if isinstance(res, tuple):
        return tuple(Tensor(np.asarray(r)) for r in res)
    return Tensor(np.asarray(res))


def nonzero(x, as_tuple=False):
    x = as_tensor(x)
    idx = np.nonzero(np.asarray(x._array))
    if as_tuple:
        return tuple(Tensor(i) for i in idx)
    return Tensor(np.stack(idx, axis=-1))


def bincount(x, weights=None, minlength=0):
    x = as_tensor(x)
    w = weights._array if isinstance(weights, Tensor) else weights
    return apply_nograd(
        "bincount", lambda a: jnp.bincount(a, weights=w, minlength=minlength), x
    )


def nanmedian(x, axis=None, keepdim=False, name=None):
    x = as_tensor(x)
    return apply("nanmedian",
                 lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim),
                 x)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Trapezoidal integration (paddle.trapezoid)."""
    if x is not None and dx is not None:
        raise ValueError("trapezoid: pass x (coordinates) OR dx "
                         "(uniform spacing), not both")
    y = as_tensor(y)
    xs = None if x is None else \
        (x._array if isinstance(x, Tensor) else jnp.asarray(x))
    d = 1.0 if dx is None else float(dx)

    def fn(a):
        if xs is not None:
            return jax.scipy.integrate.trapezoid(a, x=xs, axis=axis)
        return jax.scipy.integrate.trapezoid(a, dx=d, axis=axis)

    return apply("trapezoid", fn, y)
