"""On-device categorical sampling + rejection-sampling speculative
acceptance — the op tier under the probabilistic serving subsystem
(paddle_tpu/inference/sampling.py wires it into the engine).

Per-slot sampling params (temperature / top-k / top-p) arrive as TRACED
per-row arrays — params are DATA, never trace keys, so one compiled
decode/verify program serves every live mix of greedy and sampled lanes
(the engine's `decode_traces == 1` contract is unchanged by sampling).
Greedy lanes (`temperature <= 0`) take the literal `jnp.argmax` the
pre-sampling engine computed — same op over the same logits, so their
token streams are BIT-identical to the greedy engine's.

Randomness is keyed per (request seed, absolute position): each slot
carries a `[2]` uint32 base key row (derived host-side from its
request's seed, threaded beside the pools as a `[slots, 2]` array) and
every draw folds the row's absolute position plus a draw-purpose salt
into it — `fold_in(fold_in(base, position), salt)` — so

- no key is ever consumed twice (tpu-lint TPU003 clean by
  construction: `fold_in` is a key DERIVER, and each derived key feeds
  exactly one sampler);
- the token at absolute position P+1 is always drawn with the key
  folded from P, whatever path produced it (chunked prefill's final
  chunk, bucketed prefill, a full-prefix-hit decode, a speculative
  bonus draw) — same (seed, trace, config) => same tokens, and the
  prefill modes / cold / warm runs agree token-for-token;
- the draws are backend-independent (they consume logits AFTER
  attention), so sampled streams are identical across the dense and
  pallas backends wherever the greedy streams are.

Rejection-sampling speculative acceptance (`verify_window`): the
engine's drafters are DETERMINISTIC (n-gram lookup, greedy tiny-GPT),
i.e. the draft distribution q is a point mass at the proposed token —
the Leviathan et al. ("Fast Inference from Transformers via
Speculative Decoding") accept test `u < min(1, p(x)/q(x))` reduces to
`u < p(x)`, and the residual resample `norm(max(p - q, 0))` reduces to
p with the rejected token's mass zeroed (renormalized by the softmax).
That preserves the target distribution EXACTLY: the emitted marginal is
`p(d)*1[x=d] + (1-p(d)) * p(x)/(1-p(d)) = p(x)` — a draft can change
which random numbers are consumed, never what distribution the stream
is drawn from. Greedy lanes run the same structure with the accept
test degraded to argmax EQUALITY and every choice pinned to argmax, so
the host's uniform walk (`drafts[:n] + choices[n]`) reproduces the
exact-acceptance token stream bit-for-bit.

All functions here are raw-jnp compiled-step bodies (the
`copy_pool_block` precedent), not user-facing Tensor ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["masked_logits", "sample_token", "verify_window",
           "SALT_SAMPLE", "SALT_ACCEPT"]

#: Draw-purpose salts folded into the per-(slot, position) key: the
#: categorical draw (plain sample / speculative bonus / rejection
#: resample — mutually exclusive uses of one row, so they share a
#: stream) and the acceptance uniform must be independent.
SALT_SAMPLE = 0
SALT_ACCEPT = 1


def masked_logits(logits, temps, top_ks, top_ps):
    """Temperature/top-k/top-p masking, fused before the sample.

    `logits` `[N, V]` (any float dtype), `temps`/`top_ks`/`top_ps`
    `[N]` per-row params -> fp32 logits whose softmax is each row's
    sampling distribution: scaled by `1/temperature`, then everything
    below the k-th largest scaled logit masked to -inf (`top_k <= 0`
    = off), then the nucleus mask — ranked by descending probability,
    a token survives iff the cumulative mass BEFORE it is < `top_p`
    (the crossing token stays, so at least the argmax always
    survives). Greedy rows (`temperature <= 0`) are scaled by 1.0 —
    their masked logits are junk the callers never select (they take
    the argmax path instead)."""
    lg = logits.astype(jnp.float32)
    N, V = lg.shape
    safe_t = jnp.where(temps <= 0.0, 1.0,
                       temps.astype(jnp.float32))
    lg = lg / safe_t[:, None]
    # ONE descending sort serves both masks (this runs in the hot
    # decode/verify step): argsort is stable, so ties resolve
    # deterministically and runs reproduce. Top-k -infs entries below
    # the k-th largest; their descending rank doesn't move and their
    # probability is 0, so the nucleus cumsum over the UNMASKED order
    # is identical to one over the masked order.
    order = jnp.argsort(-lg, axis=-1)
    desc = jnp.take_along_axis(lg, order, axis=-1)
    k = jnp.where(top_ks <= 0, V, top_ks)
    kth = jnp.take_along_axis(desc, jnp.clip(k - 1, 0, V - 1)[:, None],
                              axis=1)
    lg = jnp.where(lg >= kth, lg, -jnp.inf)
    p_desc = jax.nn.softmax(jnp.where(desc >= kth, desc, -jnp.inf),
                            axis=-1)
    cum = jnp.cumsum(p_desc, axis=-1)
    keep_desc = (cum - p_desc) < top_ps.astype(jnp.float32)[:, None]
    keep_desc = keep_desc.at[:, 0].set(True)   # argmax always survives
    # un-permute by scatter (O(V)) instead of a second argsort: a
    # True landing on a top-k-masked entry keeps -inf either way
    keep = jnp.zeros((N, V), bool) \
        .at[jnp.arange(N)[:, None], order].set(keep_desc)
    return jnp.where(keep, lg, -jnp.inf)


def _draw_categorical(lg, key_rows, positions, salt):
    """One categorical draw per row of `lg` `[N, V]`: row i's key is
    `fold_in(fold_in(key_rows[i], positions[i]), salt)` — consumed by
    exactly one sampler."""
    def one(row_key, pos, row_lg):
        k = jax.random.fold_in(row_key, pos)
        return jax.random.categorical(jax.random.fold_in(k, salt),
                                      row_lg)

    return jax.vmap(one)(key_rows, positions, lg)


def _draw_uniform(key_rows, positions, salt):
    """One U[0, 1) per (row, position) — the acceptance test's coin."""
    def one(row_key, pos):
        k = jax.random.fold_in(row_key, pos)
        return jax.random.uniform(jax.random.fold_in(k, salt))

    return jax.vmap(one)(key_rows, positions)


def sample_token(logits, temps, top_ks, top_ps, key_rows, positions):
    """Per-row next token from `[N, V]` logits: greedy rows
    (`temperature <= 0`) take the literal `jnp.argmax` — bit-identical
    to the pre-sampling engine — and sampled rows a categorical draw
    from the masked distribution, keyed by the row's (seed, position).
    `key_rows` `[N, 2]` uint32, `positions` `[N]` int32 (the absolute
    position whose logits these are — the emitted token lands at
    position + 1). Returns `[N]` int32."""
    am = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lg = masked_logits(logits, temps, top_ks, top_ps)
    drawn = _draw_categorical(lg, key_rows, positions,
                              SALT_SAMPLE).astype(jnp.int32)
    return jnp.where(temps <= 0.0, am, drawn)


def verify_window(logits, tokens, draft_lens, temps, top_ks, top_ps,
                  key_rows, positions):
    """Rejection-sampling acceptance over one speculative verify
    window — all K+1 logit positions are already in hand, so per-slot
    accept/resample happens on-device in the same compiled program.

    `logits` `[B, W, V]` (window row j's distribution p_j governs the
    token AFTER row j), `tokens` `[B, W]` the window's input tokens
    (feed token at row 0, drafts after it), `draft_lens` `[B]`,
    per-slot `temps`/`top_ks`/`top_ps` `[B]`, `key_rows` `[B, 2]`
    uint32, `positions` `[B]` row-0 absolute positions. Returns

    - `accepts` `[B, W]` bool: row j tests the DRAFT in window row
      j+1 against p_j — sampled lanes the Leviathan coin
      `u < p_j(d)` (deterministic drafter: q is a point mass), greedy
      lanes exact argmax equality; False past the draft length.
    - `choices` `[B, W]` int32: the token to emit when the host's
      acceptance walk STOPS at row j — the residual resample
      `norm(max(p_j - q_j, 0))` (p_j with the rejected draft's mass
      zeroed) while a draft exists at row j+1, the plain bonus draw
      from p_j at j == draft_len; greedy lanes pin argmax.

    Host contract (`GenerationEngine._spec_decode_step`): accept the
    longest prefix `n` with `accepts[:, :n]` all true, emit
    `drafts[:n] + [choices[n]]` — for greedy lanes that reproduces the
    exact-acceptance stream bit-for-bit, for sampled lanes it provably
    preserves the target distribution (see the module docstring)."""
    B, W, V = logits.shape
    am = jnp.argmax(logits, axis=-1).astype(jnp.int32)     # [B, W]
    rep = lambda a: jnp.repeat(a, W)       # [B] params -> [B*W] rows
    lg = masked_logits(logits.reshape(B * W, V), rep(temps),
                       rep(top_ks), rep(top_ps)).reshape(B, W, V)
    probs = jax.nn.softmax(lg, axis=-1)                    # fp32
    # the draft row j tests is window row j+1 (none at the last row)
    d_next = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros((B, 1), tokens.dtype)],
        axis=1).astype(jnp.int32)
    has_draft = jnp.arange(W)[None, :] < draft_lens[:, None]
    p_d = jnp.take_along_axis(probs, d_next[..., None],
                              axis=-1)[..., 0]             # [B, W]
    wpos = positions[:, None] + jnp.arange(W)[None, :]     # [B, W]
    keys_flat = jnp.repeat(key_rows, W, axis=0)            # [B*W, 2]
    u = _draw_uniform(keys_flat, wpos.reshape(-1),
                      SALT_ACCEPT).reshape(B, W)
    greedy = (temps <= 0.0)[:, None]
    accepts = jnp.where(greedy, d_next == am, u < p_d) & has_draft
    # the stop-row choice: zero the rejected draft's mass while a
    # draft exists (the softmax inside categorical renormalizes —
    # exactly norm(max(p - q, 0)) for a point-mass q); the j == dlen
    # row keeps p whole, which is the bonus draw — and the SAME
    # (position, salt) stream a K=0 decode step would consume, so
    # all-accepted sampled chains match the draftless stream's draws
    excl = has_draft[..., None] \
        & (jnp.arange(V)[None, None, :] == d_next[..., None])
    fb_lg = jnp.where(excl, -jnp.inf, lg)
    drawn = _draw_categorical(fb_lg.reshape(B * W, V), keys_flat,
                              wpos.reshape(-1),
                              SALT_SAMPLE).astype(jnp.int32)
    choices = jnp.where(greedy, am, drawn.reshape(B, W))
    return choices, accepts
