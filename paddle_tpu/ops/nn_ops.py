"""Neural-net structured ops: conv, pooling, normalization, embedding,
dropout, losses, attention.

Analogs of paddle/phi/kernels/{conv_kernel,pool_kernel,batch_norm_kernel,
layer_norm_kernel,embedding_kernel,softmax_kernel}.* and the fused ops in
paddle/fluid/operators/fused/. On TPU, convs and matmuls hit the MXU via
lax.conv_general_dilated / dot_general; "fusion" is XLA's job, so the
fused_* surface is expressed as single jax fns that compile to one
computation.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import random as random_mod
from paddle_tpu.core.random import next_key
from paddle_tpu.core.tensor import Tensor

from .dispatch import apply, apply_nograd, as_tensor

__all__ = [
    "linear", "conv2d", "conv1d", "conv2d_transpose", "conv3d",
    "max_pool2d", "avg_pool2d", "adaptive_avg_pool2d", "adaptive_max_pool2d",
    "max_pool1d", "avg_pool1d", "global_avg_pool2d",
    "batch_norm", "layer_norm", "group_norm", "instance_norm", "rms_norm",
    "embedding", "dropout", "dropout2d",
    "softmax_with_cross_entropy", "cross_entropy", "mse_loss", "l1_loss",
    "nll_loss", "bce_loss", "bce_with_logits", "smooth_l1_loss",
    "kl_div", "cosine_similarity", "margin_ranking_loss", "hinge_embedding_loss",
    "scaled_dot_product_attention", "interpolate", "pixel_shuffle",
    "fused_bias_dropout_residual_layer_norm", "label_smooth", "temporal_shift",
    "unfold", "fold", "grid_sample", "affine_grid",
    "max_pool3d", "avg_pool3d", "normalize", "local_response_norm",
    "dropout3d", "alpha_dropout", "pixel_unshuffle", "sequence_mask",
    "square_error_cost", "log_loss", "sigmoid_focal_loss", "dice_loss",
    "npair_loss", "triplet_margin_loss", "cosine_embedding_loss",
    "margin_cross_entropy", "ctc_loss",
]


# ---------------------------------------------------------------------------
# dense / conv
# ---------------------------------------------------------------------------

def linear(x, weight, bias=None):
    """y = x @ W + b. Analog of phi MatmulKernel+AddKernel; the reference's
    F.linear (python/paddle/nn/functional/common.py:1814). Weight layout is
    [in, out] (paddle convention)."""
    x, weight = as_tensor(x), as_tensor(weight)

    if bias is None:
        def fn(a, w):
            pet = jnp.float32 if jnp.issubdtype(a.dtype, jnp.floating) else None
            return jnp.matmul(a, w, preferred_element_type=pet).astype(
                jnp.promote_types(a.dtype, w.dtype)
            )

        return apply("linear", fn, x, weight)

    bias = as_tensor(bias)

    def fnb(a, w, b):
        pet = jnp.float32 if jnp.issubdtype(a.dtype, jnp.floating) else None
        out = jnp.matmul(a, w, preferred_element_type=pet)
        return (out + b.astype(out.dtype)).astype(jnp.promote_types(a.dtype, w.dtype))

    return apply("linear", fnb, x, weight, bias)


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _conv_padding(padding, k, stride, dilation, nsp):
    """Paddle padding spec -> lax padding list."""
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, (list, tuple)):
        if len(padding) == nsp:
            return [(int(p), int(p)) for p in padding]
        if len(padding) == 2 * nsp:
            return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(nsp)]
    p = int(padding)
    return [(p, p)] * nsp


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    """2-D convolution on the MXU. Weight layout OIHW (paddle). Analog of
    phi Conv2dKernel (paddle/phi/kernels/conv_kernel.h)."""
    x, weight = as_tensor(x), as_tensor(weight)
    stride = _pair(stride)
    dilation = _pair(dilation)
    pad = _conv_padding(padding, None, stride, dilation, 2)

    def fn(a, w):
        # Compute ALWAYS runs NHWC/HWIO internally: the TPU conv engine
        # is an order of magnitude faster with channels-last operands
        # (measured on v5e, 28x28x256 3x3: 236 vs 20 TFLOPS). For NCHW
        # callers the wrapping transposes cancel between consecutive
        # layers inside one XLA program (algebraic simplifier moves them
        # through the elementwise/BN ops), so the paddle-default NCHW
        # API costs at most one transpose at each graph boundary.
        if data_format == "NCHW":
            a = jnp.transpose(a, (0, 2, 3, 1))
        w = jnp.transpose(w, (2, 3, 1, 0))  # OIHW -> HWIO
        # no preferred_element_type: the MXU accumulates bf16 convs in
        # fp32 natively, and an explicit fp32 output breaks the conv
        # transpose rule under AD (fp32 cotangent vs bf16 weight)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=("NHWC", "HWIO",
                                                      "NHWC"),
            feature_group_count=groups,
        ).astype(a.dtype)
        if data_format == "NCHW":
            out = jnp.transpose(out, (0, 3, 1, 2))
        return out

    out = apply("conv2d", fn, x, weight)
    if bias is not None:
        bias = as_tensor(bias)
        bshape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = apply("conv2d_bias", lambda o, b: o + b.reshape(bshape), out, bias)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    x, weight = as_tensor(x), as_tensor(weight)
    stride = _pair(stride, 1)
    dilation = _pair(dilation, 1)
    pad = _conv_padding(padding, None, stride, dilation, 1)
    dn = ("NCH", "OIH", "NCH")

    def fn(a, w):
        return jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups,
        ).astype(a.dtype)

    out = apply("conv1d", fn, x, weight)
    if bias is not None:
        out = apply("conv1d_bias", lambda o, b: o + b.reshape(1, -1, 1), out, as_tensor(bias))
    return out


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    x, weight = as_tensor(x), as_tensor(weight)
    stride = _pair(stride, 3)
    dilation = _pair(dilation, 3)
    pad = _conv_padding(padding, None, stride, dilation, 3)
    dn = ("NCDHW", "OIDHW", "NCDHW")

    def fn(a, w):
        return jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups,
        ).astype(a.dtype)

    out = apply("conv3d", fn, x, weight)
    if bias is not None:
        out = apply("conv3d_bias", lambda o, b: o + b.reshape(1, -1, 1, 1, 1), out, as_tensor(bias))
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, data_format="NCHW"):
    """Transposed conv — analog of phi Conv2dTransposeKernel. Weight IOHW."""
    x, weight = as_tensor(x), as_tensor(weight)
    stride = _pair(stride)
    dilation = _pair(dilation)
    opad = _pair(output_padding)
    p = _conv_padding(padding, None, stride, dilation, 2)
    if isinstance(p, str):
        raise NotImplementedError("string padding for conv_transpose")

    def fn(a, w):
        # lax.conv_transpose with paddle's conv-grad-style padding math
        kh = (w.shape[2] - 1) * dilation[0] + 1
        kw = (w.shape[3] - 1) * dilation[1] + 1
        pad_cfg = [
            (kh - 1 - p[0][0], kh - 1 - p[0][1] + opad[0]),
            (kw - 1 - p[1][0], kw - 1 - p[1][1] + opad[1]),
        ]
        w_flip = jnp.flip(w, axis=(2, 3))  # IOHW flipped
        w_t = jnp.swapaxes(w_flip, 0, 1)  # -> OIHW with O=out channels
        if groups > 1:
            # grouped transpose: weight is (in, out/g, kh, kw)
            i, og, KH, KW = w.shape
            wg = w_flip.reshape(groups, i // groups, og, KH, KW)
            wg = jnp.swapaxes(wg, 1, 2).reshape(groups * og, i // groups, KH, KW)
            w_t = wg
        return jax.lax.conv_general_dilated(
            a, w_t, window_strides=(1, 1), padding=pad_cfg,
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=groups,
        ).astype(a.dtype)

    out = apply("conv2d_transpose", fn, x, weight)
    if bias is not None:
        out = apply("convt_bias", lambda o, b: o + b.reshape(1, -1, 1, 1), out, as_tensor(bias))
    return out


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

def _pool2d(x, kernel_size, stride, padding, init, op, norm=False, ceil_mode=False):
    ks = _pair(kernel_size)
    st = _pair(stride if stride is not None else kernel_size)
    pd = _conv_padding(padding, ks, st, (1, 1), 2)
    if isinstance(pd, str):
        pad_cfg = pd
    else:
        pad_cfg = [(0, 0), (0, 0)] + list(pd)

    def fn(a):
        window = (1, 1) + ks
        strides = (1, 1) + st
        out = jax.lax.reduce_window(
            a, init, op, window, strides,
            padding=pad_cfg if isinstance(pad_cfg, str) else pad_cfg,
        )
        if norm:
            ones = jnp.ones_like(a)
            cnt = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, window, strides,
                padding=pad_cfg if isinstance(pad_cfg, str) else pad_cfg,
            )
            out = out / cnt
        return out

    return fn


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NCHW"):
    x = as_tensor(x)
    fn = _pool2d(x, kernel_size, stride, padding, -jnp.inf, jax.lax.max)
    return apply("max_pool2d", fn, x)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               count_include_pad=True, data_format="NCHW"):
    x = as_tensor(x)
    if count_include_pad:
        ks = _pair(kernel_size)
        scale = 1.0 / (ks[0] * ks[1])
        raw = _pool2d(x, kernel_size, stride, padding, 0.0, jax.lax.add)
        return apply("avg_pool2d", lambda a: raw(a) * scale, x)
    fn = _pool2d(x, kernel_size, stride, padding, 0.0, jax.lax.add, norm=True)
    return apply("avg_pool2d", fn, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False):
    x = as_tensor(x)
    ks = int(kernel_size) if not isinstance(kernel_size, (list, tuple)) else int(kernel_size[0])
    st = ks if stride is None else (int(stride) if not isinstance(stride, (list, tuple)) else int(stride[0]))
    pd = int(padding) if not isinstance(padding, (list, tuple)) else int(padding[0])

    def fn(a):
        return jax.lax.reduce_window(
            a, -jnp.inf, jax.lax.max, (1, 1, ks), (1, 1, st),
            padding=[(0, 0), (0, 0), (pd, pd)],
        )

    return apply("max_pool1d", fn, x)


def avg_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False):
    x = as_tensor(x)
    ks = int(kernel_size) if not isinstance(kernel_size, (list, tuple)) else int(kernel_size[0])
    st = ks if stride is None else (int(stride) if not isinstance(stride, (list, tuple)) else int(stride[0]))
    pd = int(padding) if not isinstance(padding, (list, tuple)) else int(padding[0])

    def fn(a):
        s = jax.lax.reduce_window(
            a, 0.0, jax.lax.add, (1, 1, ks), (1, 1, st),
            padding=[(0, 0), (0, 0), (pd, pd)],
        )
        return s / ks

    return apply("avg_pool1d", fn, x)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    x = as_tensor(x)
    oh, ow = _pair(output_size)
    H, W = x.shape[2], x.shape[3]
    if H % oh == 0 and W % ow == 0:
        kh, kw = H // oh, W // ow

        def fn(a):
            n, c = a.shape[0], a.shape[1]
            a = a.reshape(n, c, oh, kh, ow, kw)
            return a.mean(axis=(3, 5))

        return apply("adaptive_avg_pool2d", fn, x)
    raise NotImplementedError("adaptive pool with non-divisible sizes")


def adaptive_max_pool2d(x, output_size, data_format="NCHW"):
    x = as_tensor(x)
    oh, ow = _pair(output_size)
    H, W = x.shape[2], x.shape[3]
    if H % oh == 0 and W % ow == 0:
        kh, kw = H // oh, W // ow

        def fn(a):
            n, c = a.shape[0], a.shape[1]
            a = a.reshape(n, c, oh, kh, ow, kw)
            return a.max(axis=(3, 5))

        return apply("adaptive_max_pool2d", fn, x)
    raise NotImplementedError("adaptive pool with non-divisible sizes")


def global_avg_pool2d(x):
    x = as_tensor(x)
    return apply("global_avg_pool2d", lambda a: a.mean(axis=(2, 3), keepdims=True), x)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW"):
    """BatchNorm. Analog of phi BatchNormKernel
    (paddle/phi/kernels/batch_norm_kernel.h). Running stats are updated
    in-place on the Tensor objects in training mode (eager semantics)."""
    x = as_tensor(x)
    c_axis = 1 if data_format == "NCHW" else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]
    bshape = tuple(bshape)

    if training:
        def fn(a, *wb):
            # E[x^2] - m^2 instead of a.var(): both reductions fuse into
            # ONE pass over the activation (a.var needs the mean first,
            # i.e. a second full read) — BN traffic is the dominant cost
            # of conv nets on TPU (profiled: elementwise/reduce fusions
            # dwarf the convs on resnet50). Stats accumulate in f32 (the
            # convert fuses into the reduction read; bf16 m2-m^2 loses to
            # cancellation) and var clamps at 0.
            af = a.astype(jnp.float32)
            mean32 = af.mean(axis=reduce_axes)
            m2 = (af * af).mean(axis=reduce_axes)
            var32 = jnp.maximum(m2 - mean32 * mean32, 0.0)
            mean = mean32.astype(a.dtype)
            var = var32.astype(a.dtype)
            inv = jax.lax.rsqrt(var32.reshape(bshape) + epsilon) \
                .astype(a.dtype)
            out = (a - mean.reshape(bshape)) * inv
            i = 0
            if weight is not None:
                out = out * wb[i].reshape(bshape)
                i += 1
            if bias is not None:
                out = out + wb[i].reshape(bshape)
            return out, mean, var

        ins = [x]
        if weight is not None:
            ins.append(as_tensor(weight))
        if bias is not None:
            ins.append(as_tensor(bias))
        out, mean, var = apply("batch_norm", fn, *ins)

        # update running stats (stop-gradient side effect)
        if running_mean is not None:
            rm = running_mean._array if isinstance(running_mean, Tensor) else running_mean
            rv = running_var._array if isinstance(running_var, Tensor) else running_var
            n = float(np.prod([x.shape[i] for i in reduce_axes]))
            unbiased = var._array * (n / max(n - 1.0, 1.0))
            running_mean._array = momentum * rm + (1 - momentum) * jax.lax.stop_gradient(mean._array)
            running_var._array = momentum * rv + (1 - momentum) * jax.lax.stop_gradient(unbiased)
        return out

    rm = running_mean._array if isinstance(running_mean, Tensor) else jnp.asarray(running_mean)
    rv = running_var._array if isinstance(running_var, Tensor) else jnp.asarray(running_var)

    def infer_fn(a, *wb):
        inv = jax.lax.rsqrt(rv.reshape(bshape) + epsilon)
        out = (a - rm.reshape(bshape)) * inv
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        return out

    ins = [x]
    if weight is not None:
        ins.append(as_tensor(weight))
    if bias is not None:
        ins.append(as_tensor(bias))
    return apply("batch_norm_infer", infer_fn, *ins)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    """LayerNorm over trailing dims. Analog of phi LayerNormKernel; computed
    in fp32 for bf16 inputs (TPU numerics best practice)."""
    x = as_tensor(x)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    naxes = tuple(range(x.ndim - len(normalized_shape), x.ndim))

    def fn(a, *wb):
        orig = a.dtype
        af = a.astype(jnp.float32) if a.dtype in (jnp.bfloat16, jnp.float16) else a
        mean = af.mean(axis=naxes, keepdims=True)
        var = af.var(axis=naxes, keepdims=True)
        out = (af - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(out.dtype)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(out.dtype)
        return out.astype(orig)

    ins = [x]
    if weight is not None:
        ins.append(as_tensor(weight))
    if bias is not None:
        ins.append(as_tensor(bias))
    return apply("layer_norm", fn, *ins)


def rms_norm(x, weight=None, epsilon=1e-6):
    """RMSNorm (no reference analog in v2.4 — modern LLM staple)."""
    x = as_tensor(x)

    def fn(a, *w):
        orig = a.dtype
        af = a.astype(jnp.float32) if a.dtype in (jnp.bfloat16, jnp.float16) else a
        ms = jnp.mean(jnp.square(af), axis=-1, keepdims=True)
        out = af * jax.lax.rsqrt(ms + epsilon)
        if w:
            out = out * w[0].astype(out.dtype)
        return out.astype(orig)

    ins = [x] + ([as_tensor(weight)] if weight is not None else [])
    return apply("rms_norm", fn, *ins)


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    x = as_tensor(x)
    C = x.shape[1]

    def fn(a, *wb):
        n = a.shape[0]
        g = num_groups
        rest = a.shape[2:]
        a2 = a.reshape(n, g, C // g, *rest)
        axes = tuple(range(2, a2.ndim))
        mean = a2.mean(axis=axes, keepdims=True)
        var = a2.var(axis=axes, keepdims=True)
        out = ((a2 - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a.shape)
        bshape = (1, C) + (1,) * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        return out

    ins = [x]
    if weight is not None:
        ins.append(as_tensor(weight))
    if bias is not None:
        ins.append(as_tensor(bias))
    return apply("group_norm", fn, *ins)


def instance_norm(x, weight=None, bias=None, epsilon=1e-5):
    x = as_tensor(x)
    axes = tuple(range(2, x.ndim))
    C = x.shape[1]

    def fn(a, *wb):
        mean = a.mean(axis=axes, keepdims=True)
        var = a.var(axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + epsilon)
        bshape = (1, C) + (1,) * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(bshape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(bshape)
        return out

    ins = [x]
    if weight is not None:
        ins.append(as_tensor(weight))
    if bias is not None:
        ins.append(as_tensor(bias))
    return apply("instance_norm", fn, *ins)


# ---------------------------------------------------------------------------
# embedding / dropout
# ---------------------------------------------------------------------------

def embedding(ids, weight, padding_idx=None, sparse=False):
    """Embedding lookup. Analog of phi EmbeddingKernel
    (paddle/phi/kernels/embedding_kernel.h). The backward is a dense
    scatter-add (XLA turns it into an efficient segment-sum on TPU);
    SelectedRows-style sparse grads are intentionally not replicated —
    under SPMD the all-to-all embedding path in distributed/ covers the
    sparse scale-out case."""
    ids_t = as_tensor(ids)
    weight = as_tensor(weight)
    idx = ids_t._array

    def fn(w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return apply("embedding", fn, weight)


_dropout_trace_warned = False


def _warn_if_constant_key(arr, opname):
    """One-time warning shared by every op that draws a PRNG key at
    trace time: outside a key scope the key is baked as a constant and
    every execution reuses the same mask/noise."""
    global _dropout_trace_warned
    if isinstance(arr, jax.core.Tracer) and not random_mod.in_key_scope():
        if not _dropout_trace_warned:
            import warnings

            warnings.warn(
                f"{opname} traced with a constant PRNG key: every "
                "execution of this compiled function will reuse the SAME "
                "random draw. Use jit.TrainStep (which threads a per-step "
                "key) or wrap the call in "
                "paddle_tpu.core.random.key_scope(key).")
            _dropout_trace_warned = True


def dropout(x, p=0.5, training=True, mode="upscale_in_train", axis=None):
    """Dropout. Analog of phi DropoutKernel. RNG comes from the global
    Generator key chain (core/random.py); inside a compiled step the key
    derives from the step's traced key (random.key_scope) so every step
    gets a fresh mask. Tracing dropout OUTSIDE a key scope would bake a
    constant key (identical mask every step) — warn loudly."""
    x = as_tensor(x)
    if not training or p == 0.0:
        return x
    _warn_if_constant_key(x._array, "dropout")
    key = next_key()
    keep = 1.0 - p

    def fn(a):
        shape = a.shape if axis is None else tuple(
            a.shape[i] if i in (axis if isinstance(axis, (list, tuple)) else [axis]) else 1
            for i in range(a.ndim)
        )
        mask = jax.random.bernoulli(key, keep, shape)
        if mode == "upscale_in_train":
            return jnp.where(mask, a / keep, 0.0).astype(a.dtype)
        return jnp.where(mask, a, 0.0).astype(a.dtype)

    return apply("dropout", fn, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, training, axis=axis)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def softmax_with_cross_entropy(logits, label, soft_label=False, axis=-1,
                               ignore_index=-100):
    """Fused softmax+CE. Analog of phi CrossEntropyWithSoftmaxKernel
    (paddle/phi/kernels/cross_entropy_kernel.h) and the mp variant
    _c_softmax_with_cross_entropy (mp_ops.py:375)."""
    logits = as_tensor(logits)
    if soft_label:
        label_t = as_tensor(label)

        def fn(lg, lb):
            logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=axis)
            return -jnp.sum(lb * logp, axis=axis, keepdims=True)

        return apply("softmax_ce_soft", fn, logits, label_t)

    lab = label._array if isinstance(label, Tensor) else jnp.asarray(label)
    if lab.ndim == logits.ndim and lab.shape[axis] == 1:
        lab = jnp.squeeze(lab, axis)

    def fn(lg):
        logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=axis)
        idx = jnp.expand_dims(lab, axis).astype(jnp.int32)
        mask = idx != ignore_index
        ll = jnp.take_along_axis(logp, jnp.where(mask, idx, 0), axis=axis)
        loss = jnp.where(mask, -ll, 0.0)
        return loss.astype(lg.dtype)

    return apply("softmax_ce", fn, logits)


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0):
    """Analog of paddle.nn.functional.cross_entropy
    (python/paddle/nn/functional/loss.py). use_softmax=False means `input`
    is already a probability distribution over `axis` (paddle semantics):
    the loss is plain NLL -log(p[label]) / -sum(label*log(p))."""
    input = as_tensor(input)

    def _hard_labels():
        lab = label._array if isinstance(label, Tensor) else jnp.asarray(label)
        if lab.ndim == input.ndim and lab.shape[axis] == 1:
            lab = jnp.squeeze(lab, axis)
        return lab

    # keep the ORIGINAL hard labels: weight selection and the valid-count
    # must index by them even after label smoothing converts to soft
    hard_lab = None if soft_label else _hard_labels()

    smoothed = label_smoothing > 0.0 and not soft_label
    if smoothed:
        num_classes = input.shape[axis]
        onehot = jax.nn.one_hot(hard_lab, num_classes, dtype=jnp.float32,
                                axis=axis)
        soft = onehot * (1 - label_smoothing) + label_smoothing / num_classes
        label = Tensor._wrap(soft)
        soft_label = True

    if use_softmax:
        loss = softmax_with_cross_entropy(
            input, label, soft_label=soft_label, axis=axis,
            ignore_index=ignore_index)
    else:
        # input is probabilities: NLL without the softmax
        if soft_label:
            label_t = as_tensor(label)
            loss = apply(
                "nll_soft",
                lambda p, lb: -jnp.sum(
                    lb * jnp.log(jnp.maximum(p.astype(jnp.float32), 1e-30)),
                    axis=axis, keepdims=True),
                input, label_t)
        else:
            idx = jnp.expand_dims(hard_lab, axis).astype(jnp.int32)
            mask = idx != ignore_index

            def fn(p):
                logp = jnp.log(jnp.maximum(p.astype(jnp.float32), 1e-30))
                ll = jnp.take_along_axis(logp, jnp.where(mask, idx, 0),
                                         axis=axis)
                return jnp.where(mask, -ll, 0.0).astype(p.dtype)

            loss = apply("nll_hard", fn, input)

    if smoothed:
        # the soft-CE path has no ignore_index masking: zero ignored rows
        # so the valid-count mean below stays correct
        ig_mask = jnp.expand_dims(hard_lab != ignore_index, axis)
        loss = apply("ce_ignore_mask",
                     lambda l: jnp.where(ig_mask, l, 0.0).astype(l.dtype),
                     loss)

    wsel = None
    if weight is not None:
        if hard_lab is None:
            raise ValueError(
                "weight with soft_label=True is not supported (pass hard "
                "labels, optionally with label_smoothing)")
        w = weight._array if isinstance(weight, Tensor) else jnp.asarray(weight)
        safe_lab = jnp.where(hard_lab == ignore_index, 0, hard_lab)
        wsel = jnp.where(hard_lab == ignore_index, 0.0,
                         jnp.take(w, safe_lab.astype(jnp.int32)))
        loss = apply("ce_weight",
                     lambda l: l * jnp.expand_dims(wsel, axis).astype(l.dtype),
                     loss)

    loss_sq = apply("squeeze_loss", lambda l: jnp.squeeze(l, axis), loss)
    if reduction == "none":
        return loss_sq
    if reduction == "mean" and hard_lab is not None:
        # paddle semantics: mean over non-ignored labels; with class
        # weights the denominator is the sum of selected weights
        if wsel is not None:
            return apply(
                "reduce_loss",
                lambda l: jnp.sum(l) / jnp.maximum(jnp.sum(wsel), 1e-12),
                loss_sq)
        valid = (hard_lab != ignore_index).astype(jnp.float32)
        return apply(
            "reduce_loss",
            lambda l: jnp.sum(l) / jnp.maximum(jnp.sum(valid), 1.0), loss_sq)
    return apply("reduce_loss", lambda l: _reduce_loss(l, reduction), loss_sq)


def mse_loss(input, label, reduction="mean"):
    input, label = as_tensor(input), as_tensor(label)
    return apply(
        "mse_loss", lambda a, b: _reduce_loss(jnp.square(a - b), reduction), input, label
    )


def l1_loss(input, label, reduction="mean"):
    input, label = as_tensor(input), as_tensor(label)
    return apply(
        "l1_loss", lambda a, b: _reduce_loss(jnp.abs(a - b), reduction), input, label
    )


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):
    input, label = as_tensor(input), as_tensor(label)

    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce_loss(loss, reduction)

    return apply("smooth_l1", fn, input, label)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):
    """NLL over log-probs; class axis is 1, input may be [N,C] or
    [N,C,d1,...] with label [N] / [N,d1,...] (paddle semantics)."""
    input = as_tensor(input)
    lab = label._array if isinstance(label, Tensor) else jnp.asarray(label)

    def fn(a):
        idx = jnp.expand_dims(lab, 1).astype(jnp.int32)  # [N,1,d1,...]
        mask = idx != ignore_index
        ll = jnp.take_along_axis(a, jnp.where(mask, idx, 0), axis=1)
        loss = jnp.squeeze(jnp.where(mask, -ll, 0.0), 1)
        valid = jnp.squeeze(mask, 1)
        if weight is not None:
            w = weight._array if isinstance(weight, Tensor) else jnp.asarray(weight)
            wsel = jnp.take(w, jnp.where(lab == ignore_index, 0, lab).astype(jnp.int32))
            wsel = jnp.where(valid, wsel, 0.0)
            loss = loss * wsel
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wsel), 1e-12)
        elif reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(loss.dtype)), 1.0)
        return _reduce_loss(loss, reduction)

    return apply("nll_loss", fn, input)


def bce_loss(input, label, weight=None, reduction="mean"):
    input, label = as_tensor(input), as_tensor(label)

    def fn(a, b):
        eps = 1e-12
        loss = -(b * jnp.log(a + eps) + (1 - b) * jnp.log(1 - a + eps))
        if weight is not None:
            loss = loss * (weight._array if isinstance(weight, Tensor) else weight)
        return _reduce_loss(loss, reduction)

    return apply("bce_loss", fn, input, label)


def bce_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None):
    logit, label = as_tensor(logit), as_tensor(label)

    def fn(a, b):
        mx = jnp.maximum(a, 0)
        loss = mx - a * b + jnp.log1p(jnp.exp(-jnp.abs(a)))
        if pos_weight is not None:
            pw = pos_weight._array if isinstance(pos_weight, Tensor) else pos_weight
            loss = loss * (b * (pw - 1) + 1)
        if weight is not None:
            loss = loss * (weight._array if isinstance(weight, Tensor) else weight)
        return _reduce_loss(loss, reduction)

    return apply("bce_logits", fn, logit, label)


def kl_div(input, label, reduction="mean"):
    input, label = as_tensor(input), as_tensor(label)

    def fn(a, b):
        loss = b * (jnp.log(jnp.maximum(b, 1e-12)) - a)
        return _reduce_loss(loss, reduction)

    return apply("kl_div", fn, input, label)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    x1, x2 = as_tensor(x1), as_tensor(x2)

    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)

    return apply("cosine_similarity", fn, x1, x2)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    input, other, label = as_tensor(input), as_tensor(other), as_tensor(label)

    def fn(a, b, l):
        return _reduce_loss(jnp.maximum(0.0, -l * (a - b) + margin), reduction)

    return apply("margin_ranking", fn, input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean"):
    input, label = as_tensor(input), as_tensor(label)

    def fn(a, l):
        loss = jnp.where(l == 1.0, a, jnp.maximum(0.0, margin - a))
        return _reduce_loss(loss, reduction)

    return apply("hinge_embedding", fn, input, label)


def label_smooth(label, prior_dist=None, epsilon=0.1):
    label = as_tensor(label)
    k = label.shape[-1]

    def fn(l):
        if prior_dist is not None:
            pd = prior_dist._array if isinstance(prior_dist, Tensor) else jnp.asarray(prior_dist)
            return (1 - epsilon) * l + epsilon * pd
        return (1 - epsilon) * l + epsilon / k

    return apply("label_smooth", fn, label)


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------

def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, scale=None):
    """Plain-XLA attention used as reference/fallback; the Pallas flash
    kernel lives in paddle_tpu/ops/pallas/flash_attention.py and is
    selected by nn.MultiHeadAttention for long sequences. Analog of the
    reference's fused_attention (operators/fused/fused_attention_op.cu,
    fmha_ref.h). Layout: [batch, seq, heads, head_dim] (paddle layout)."""
    q, k, v = as_tensor(q), as_tensor(k), as_tensor(v)
    mask_arr = attn_mask._array if isinstance(attn_mask, Tensor) else attn_mask

    # flash path: no explicit mask/dropout. flash_attention is the single
    # source of truth for routing — it checks backend + shapes internally
    # and falls back to dense XLA attention (with a logged warning, and a
    # bottom-right-aligned causal mask for Sq != Skv) when the pallas
    # kernel can't be used.
    if mask_arr is None and dropout_p == 0.0:
        from .pallas.flash_attention import flash_attention

        return apply("flash_attention",
                     lambda qa, ka, va: flash_attention(
                         qa, ka, va, causal=is_causal, scale=scale),
                     q, k, v)

    # key-only additive mask (the encoder padding mask, [B,1,1,S]):
    # the fused short-seq kernel takes it natively, so padded BERT
    # fine-tunes keep the fast path instead of falling to dense
    if (mask_arr is not None and dropout_p == 0.0 and not is_causal
            and getattr(mask_arr, "ndim", 0) == 4
            and mask_arr.shape[1] == 1 and mask_arr.shape[2] == 1):
        from .pallas.flash_attention import (_on_tpu,
                                             _shapes_ok_for_shortseq,
                                             shortseq_attention)

        Sq, Skv, D = q.shape[1], k.shape[1], q.shape[3]
        if _on_tpu() and _shapes_ok_for_shortseq(Sq, Skv, D) and \
                mask_arr.shape[0] in (1, q.shape[0]) and \
                mask_arr.shape[3] == Skv:
            km = jnp.broadcast_to(
                jnp.asarray(mask_arr)[:, 0, 0, :],
                (q.shape[0], Skv))
            try:
                return apply(
                    "flash_attention_keymask",
                    lambda qa, ka, va: shortseq_attention(
                        qa, ka, va, scale=scale, key_mask=km),
                    q, k, v)
            except Exception as e:  # noqa: BLE001 — dense still works
                import warnings

                warnings.warn(
                    f"shortseq key-mask kernel unavailable, dense "
                    f"fallback: {type(e).__name__}: {e}")

    def fn(qa, ka, va):
        d = qa.shape[-1]
        s = scale if scale is not None else 1.0 / np.sqrt(d)
        # [B,S,H,D] -> [B,H,S,D]
        qh = jnp.swapaxes(qa, 1, 2)
        kh = jnp.swapaxes(ka, 1, 2)
        vh = jnp.swapaxes(va, 1, 2)
        logits = jnp.einsum(
            "bhqd,bhkd->bhqk", qh, kh, preferred_element_type=jnp.float32
        ) * s
        if is_causal:
            S, T = logits.shape[-2], logits.shape[-1]
            # bottom-right aligned for Sq != Skv (KV-cache continuation)
            cmask = jnp.tril(jnp.ones((S, T), bool), T - S)
            logits = jnp.where(cmask, logits, -1e30)
        if mask_arr is not None:
            logits = logits + mask_arr.astype(logits.dtype)
        probs = jax.nn.softmax(logits, axis=-1).astype(qa.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
        return jnp.swapaxes(out, 1, 2)

    out = apply("sdpa", fn, q, k, v)
    if dropout_p > 0.0 and training:
        out = dropout(out, dropout_p, training=True)
    return out


# ---------------------------------------------------------------------------
# vision misc
# ---------------------------------------------------------------------------

def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW"):
    x = as_tensor(x)
    H, W = x.shape[2], x.shape[3]
    if size is None:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else (scale_factor,) * 2
        size = (int(H * sf[0]), int(W * sf[1]))
    size = tuple(int(s) for s in size)
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic"}[mode]

    def fn(a):
        n, c = a.shape[0], a.shape[1]
        if align_corners and mode != "nearest" and size[0] > 1 and size[1] > 1:
            # align_corners=True: in = o*(H-1)/(out-1). scale_and_translate
            # samples in = (o + 0.5 - t)/s - 0.5, so s=(out-1)/(H-1) and
            # t = 0.5*(1-s) makes corners map to corners exactly.
            s = jnp.asarray(
                [(size[0] - 1) / (H - 1), (size[1] - 1) / (W - 1)], jnp.float32)
            t = 0.5 * (1.0 - s)
            return jax.image.scale_and_translate(
                a, (n, c) + size, spatial_dims=(2, 3),
                scale=s, translation=t, method=method)
        return jax.image.resize(a, (n, c) + size, method=method)

    return apply("interpolate", fn, x)


def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    x = as_tensor(x)
    r = int(upscale_factor)

    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
        return a.reshape(n, c // (r * r), h * r, w * r)

    return apply("pixel_shuffle", fn, x)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    x = as_tensor(x)

    def fn(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([a[:, 1:, :fold], jnp.zeros_like(a[:, :1, :fold])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(a[:, :1, fold:2 * fold]), a[:, :-1, fold:2 * fold]], axis=1)
        rest = a[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, rest], axis=2)
        return out.reshape(nt, c, h, w)

    return apply("temporal_shift", fn, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    x = as_tensor(x)
    ks = _pair(kernel_sizes)
    st = _pair(strides)
    pd = _pair(paddings)
    dl = _pair(dilations)

    def fn(a):
        n, c, h, w = a.shape
        patches = jax.lax.conv_general_dilated_patches(
            a, ks, st, [(pd[0], pd[0]), (pd[1], pd[1])],
            rhs_dilation=dl, dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        return patches.reshape(n, c * ks[0] * ks[1], -1)

    return apply("unfold", fn, x)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True):
    x, grid = as_tensor(x), as_tensor(grid)

    def fn(a, g):
        n, c, h, w = a.shape
        gx = (g[..., 0] + 1) * (w - 1) / 2 if align_corners else ((g[..., 0] + 1) * w - 1) / 2
        gy = (g[..., 1] + 1) * (h - 1) / 2 if align_corners else ((g[..., 1] + 1) * h - 1) / 2
        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        x1, y1 = x0 + 1, y0 + 1

        def sample(yy, xx):
            mask = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            v = a[jnp.arange(n)[:, None, None], :, yc, xc]  # [n,H,W,c]
            return jnp.where(mask[..., None], v, 0.0)

        wa = (x1 - gx) * (y1 - gy)
        wb = (gx - x0) * (y1 - gy)
        wc = (x1 - gx) * (gy - y0)
        wd = (gx - x0) * (gy - y0)
        out = (sample(y0, x0) * wa[..., None] + sample(y0, x1) * wb[..., None]
               + sample(y1, x0) * wc[..., None] + sample(y1, x1) * wd[..., None])
        return jnp.transpose(out, (0, 3, 1, 2))

    return apply("grid_sample", fn, x, grid)


def affine_grid(theta, out_shape, align_corners=True):
    theta = as_tensor(theta)
    n, c, h, w = [int(s) for s in out_shape]

    def fn(th):
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) + 0.5) / h * 2 - 1
            xs = (jnp.arange(w) + 0.5) / w * 2 - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # [h,w,3]
        return jnp.einsum("nij,hwj->nhwi", th, base)

    return apply("affine_grid", fn, theta)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None, ln_scale=None,
                                           ln_bias=None, dropout_rate=0.0,
                                           epsilon=1e-5, training=True):
    """Analog of operators/fused/fused_bias_dropout_residual_layer_norm — on
    TPU it's one jax fn; XLA fuses the whole chain."""
    x, residual = as_tensor(x), as_tensor(residual)
    key = next_key() if (dropout_rate > 0.0 and training) else None

    def fn(a, r, *rest):
        i = 0
        if bias is not None:
            a = a + rest[i]
            i += 1
        if key is not None:
            keep = 1.0 - dropout_rate
            mask = jax.random.bernoulli(key, keep, a.shape)
            a = jnp.where(mask, a / keep, 0.0)
        out = a + r
        mean = out.mean(axis=-1, keepdims=True)
        var = out.var(axis=-1, keepdims=True)
        y = (out - mean) * jax.lax.rsqrt(var + epsilon)
        if ln_scale is not None:
            y = y * rest[i]
            i += 1
        if ln_bias is not None:
            y = y + rest[i]
        return y

    ins = [x, residual]
    for p in (bias, ln_scale, ln_bias):
        if p is not None:
            ins.append(as_tensor(p))
    return apply("fused_bias_dropout_residual_ln", fn, *ins)


def _triple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v, v)


def _pool3d_fn(kernel_size, stride, padding, init, op, norm=False,
               count_include_pad=True, divisor_override=None):
    ks = _triple(kernel_size)
    st = _triple(stride if stride is not None else kernel_size)
    pd = _triple(padding)
    pad_cfg = [(0, 0), (0, 0)] + [(p, p) for p in pd]

    def fn(a):
        window = (1, 1) + ks
        strides = (1, 1) + st
        out = jax.lax.reduce_window(a, init, op, window, strides,
                                    padding=pad_cfg)
        if divisor_override is not None:
            out = out / float(divisor_override)
        elif norm:
            cnt = jax.lax.reduce_window(jnp.ones_like(a), 0.0, jax.lax.add,
                                        window, strides, padding=pad_cfg)
            out = out / cnt
        elif op is jax.lax.add:
            out = out / float(np.prod(ks))
        return out

    return fn


def _check_pool3d_args(ceil_mode, data_format, return_mask=False):
    """Unsupported pool3d modes fail loudly instead of silently
    computing the wrong thing."""
    if ceil_mode:
        raise NotImplementedError("pool3d: ceil_mode=True not supported")
    if data_format != "NCDHW":
        raise NotImplementedError(
            f"pool3d: data_format={data_format!r}; NCDHW only")
    if return_mask:
        raise NotImplementedError("pool3d: return_mask not supported")


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    """MaxPool3D (phi pool3d kernel analog); x [B,C,D,H,W]."""
    _check_pool3d_args(ceil_mode, data_format, return_mask)
    x = as_tensor(x)
    return apply("max_pool3d",
                 _pool3d_fn(kernel_size, stride, padding, -jnp.inf,
                            jax.lax.max), x)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW"):
    _check_pool3d_args(ceil_mode, data_format)
    if divisor_override is not None and divisor_override <= 0:
        raise ValueError("divisor_override must be positive")
    x = as_tensor(x)
    return apply("avg_pool3d",
                 _pool3d_fn(kernel_size, stride, padding, 0.0, jax.lax.add,
                            norm=exclusive,
                            divisor_override=divisor_override), x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (phi unfold kernel analog): x [B,C,H,W] ->
    [B, C*kh*kw, L] with L = Ho*Wo. Built on
    conv_general_dilated_patches (one XLA gather, MXU-adjacent layout),
    whose blocks are already channel-major (c, kh, kw) — the same
    order paddle emits, so no reorder is needed (verified against a
    manual im2col in tests)."""
    x = as_tensor(x)
    ks = _pair(kernel_sizes)
    st = _pair(strides)
    pd = _pair(paddings)
    dl = _pair(dilations)

    def fn(a):
        # precision=HIGHEST: the patch extraction is pure data movement
        # (one-hot kernel) — default bf16 MXU precision would quantize
        # the activations, whereas the reference's im2col is exact
        p = jax.lax.conv_general_dilated_patches(
            a, ks, st, [(pd[0], pd[0]), (pd[1], pd[1])],
            rhs_dilation=dl,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            precision=jax.lax.Precision.HIGHEST)
        # p: [B, C*kh*kw, Ho, Wo] with channel-major blocks already
        B, CK, Ho, Wo = p.shape
        return p.reshape(B, CK, Ho * Wo)

    return apply("unfold", fn, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im (phi fold kernel analog): [B, C*kh*kw, L] -> [B,C,H,W],
    overlapping patches summed. Implemented as the exact transpose of
    unfold via the VJP of the patch extraction (adjoint-of-gather —
    the XLA-native formulation of the reference's scatter kernel)."""
    x = as_tensor(x)
    oh, ow = _pair(output_sizes)
    ks = _pair(kernel_sizes)
    st = _pair(strides)
    pd = _pair(paddings)
    dl = _pair(dilations)

    def fn(a):
        B = a.shape[0]
        C = a.shape[1] // (ks[0] * ks[1])

        def extract(img):
            # HIGHEST precision for the same exactness reason as unfold
            # (the vjp of an exact gather is an exact scatter-add)
            p = jax.lax.conv_general_dilated_patches(
                img, ks, st, [(pd[0], pd[0]), (pd[1], pd[1])],
                rhs_dilation=dl,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                precision=jax.lax.Precision.HIGHEST)
            return p.reshape(B, p.shape[1], -1)

        zeros = jnp.zeros((B, C, oh, ow), a.dtype)
        _, vjp = jax.vjp(extract, zeros)
        (out,) = vjp(a)
        return out

    return apply("fold", fn, x)


# ---------------------------------------------------------------------------
# functional-surface completion (losses + misc; python/paddle/nn/functional/)
# ---------------------------------------------------------------------------

def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    """L_p normalize along `axis` (functional/norm.py normalize)."""
    x = as_tensor(x)

    def fn(a):
        n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)

    return apply("normalize", fn, x)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    if data_format != "NCHW":
        raise NotImplementedError(
            f"local_response_norm: data_format={data_format!r}; NCHW only")
    x = as_tensor(x)

    def fn(a):
        half = size // 2
        summed = jax.lax.reduce_window(
            jnp.square(a), 0.0, jax.lax.add, (1, size, 1, 1), (1, 1, 1, 1),
            padding=[(0, 0), (half, size - 1 - half), (0, 0), (0, 0)])
        # paddle divides the window sum by size (avg-pool formulation)
        return a / jnp.power(k + alpha * summed / size, beta)

    return apply("local_response_norm", fn, x)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    """Channel-wise dropout for 5-D inputs (whole [D,H,W] blocks) —
    dropout2d's pattern, one more spatial dim."""
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, training, axis=axis)


def alpha_dropout(x, p=0.5, training=True, name=None):
    """SELU-preserving dropout (functional/common.py alpha_dropout):
    dropped units take the negative saturation value alpha' and the
    output is affinely rescaled a*x+b with
    a = ((1-p)(1 + p*alpha'^2))^-1/2 (Klambauer et al. 2017, keeps
    zero mean / unit variance under SELU statistics)."""
    x = as_tensor(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    a_coef = ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** -0.5
    b_coef = -a_coef * p * alpha_p
    _warn_if_constant_key(x._array, "alpha_dropout")
    key = random_mod.next_key()

    def fn(t):
        keep = jax.random.bernoulli(key, 1.0 - p, t.shape)
        return (a_coef * jnp.where(keep, t, alpha_p) + b_coef) \
            .astype(t.dtype)

    return apply("alpha_dropout", fn, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    """Inverse of pixel_shuffle: [B,C,H,W] -> [B,C*r^2,H/r,W/r]."""
    x = as_tensor(x)
    r = int(downscale_factor)
    nhwc = data_format == "NHWC"

    def fn(a):
        if nhwc:
            a = a.transpose(0, 3, 1, 2)
        B, C, H, W = a.shape
        a = a.reshape(B, C, H // r, r, W // r, r)
        out = a.transpose(0, 1, 3, 5, 2, 4).reshape(
            B, C * r * r, H // r, W // r)
        return out.transpose(0, 2, 3, 1) if nhwc else out

    return apply("pixel_unshuffle", fn, x)


def sequence_mask(lengths, maxlen=None, dtype="bool", name=None):
    """mask[i, t] = t < lengths[i] (functional sequence_mask)."""
    from paddle_tpu.core import dtype as dtypes

    lengths = as_tensor(lengths)
    if maxlen is None and isinstance(lengths._array, jax.core.Tracer):
        raise ValueError(
            "sequence_mask: maxlen is required under jit (the output "
            "shape would depend on traced values)")
    ml = int(maxlen) if maxlen is not None else \
        int(np.asarray(lengths._array).max())
    jd = dtypes.to_jax(dtype)
    return apply_nograd(
        "sequence_mask",
        lambda l: (jnp.arange(ml)[None, :] < l[..., None]).astype(jd),
        lengths)


def square_error_cost(input, label):
    input, label = as_tensor(input), as_tensor(label)
    return apply("square_error_cost", lambda a, b: (a - b) ** 2,
                 input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    input, label = as_tensor(input), as_tensor(label)
    return apply(
        "log_loss",
        lambda p, y: -y * jnp.log(p + epsilon) -
        (1.0 - y) * jnp.log(1.0 - p + epsilon), input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction="sum", name=None):
    """Focal loss on logits (functional/loss.py sigmoid_focal_loss)."""
    logit, label = as_tensor(logit), as_tensor(label)
    norm_arr = None if normalizer is None else as_tensor(normalizer)

    def fn(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            loss = loss / rest[0]
        return _reduce_loss(loss, reduction)

    args = (logit, label) + ((norm_arr,) if norm_arr is not None else ())
    return apply("sigmoid_focal_loss", fn, *args)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """1 - Dice coefficient over the trailing class dim
    (functional/loss.py dice_loss): input [N,...,C] probs, label
    [N,...,1] int."""
    input = as_tensor(input)
    label = as_tensor(label)

    def fn(p, y):
        C = p.shape[-1]
        oh = jax.nn.one_hot(y.squeeze(-1), C, dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * oh, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(oh, axis=red)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))

    return apply("dice_loss", fn, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Multi-class n-pair loss (functional/loss.py npair_loss)."""
    anchor, positive = as_tensor(anchor), as_tensor(positive)
    labels = as_tensor(labels)

    def fn(a, p, y):
        sim = a @ p.T  # [B,B]
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        same = same / same.sum(axis=1, keepdims=True)
        xent = jnp.mean(jnp.sum(
            -same * jax.nn.log_softmax(sim, axis=1), axis=1))
        # reference weights the l2 term by 0.25
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1)) +
                        jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return xent + reg

    return apply("npair_loss", fn, anchor, positive, labels)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    input, positive, negative = (as_tensor(input), as_tensor(positive),
                                 as_tensor(negative))

    def fn(a, pos, neg):
        # epsilon once per distance (numerical floor), not per element —
        # per-element would scale the "zero" distance with the feature dim
        dist = lambda u, v: (jnp.sum(jnp.abs(u - v) ** p, axis=-1)
                             + epsilon) ** (1 / p)
        dp = dist(a, pos)
        dn = dist(a, neg)
        if swap:
            dn = jnp.minimum(dn, dist(pos, neg))
        loss = jnp.maximum(dp - dn + margin, 0.0)
        return _reduce_loss(loss, reduction)

    return apply("triplet_margin_loss", fn, input, positive, negative)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    """label=1: pull together (1-cos); label=-1: push below margin."""
    input1, input2, label = (as_tensor(input1), as_tensor(input2),
                             as_tensor(label))

    def fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1),
            1e-12)
        loss = jnp.where(y > 0, 1.0 - cos,
                         jnp.maximum(cos - margin, 0.0))
        return _reduce_loss(loss, reduction)

    return apply("cosine_embedding_loss", fn, input1, input2, label)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-family margin softmax (functional margin_cross_entropy):
    logits are cosines; the target class angle gets margins
    cos(m1*θ + m2) - m3 before scaled softmax CE. (The reference's
    model-parallel group sharding is subsumed by running it under a
    pjit step with mp-sharded logits.)"""
    logits, label = as_tensor(logits), as_tensor(label)

    def fn(z, y):
        C = z.shape[-1]
        oh = jax.nn.one_hot(y, C, dtype=z.dtype)
        theta = jnp.arccos(jnp.clip(z, -1.0 + 1e-7, 1.0 - 1e-7))
        target = jnp.cos(margin1 * theta + margin2) - margin3
        adj = z * (1 - oh) + target * oh
        logp = jax.nn.log_softmax(scale * adj, axis=-1)
        loss = _reduce_loss(-jnp.sum(oh * logp, axis=-1), reduction)
        if return_softmax:
            return loss, jnp.exp(logp)
        return loss

    return apply("margin_cross_entropy", fn, logits, label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (functional/loss.py ctc_loss; phi warpctc analog) via
    the log-domain forward algorithm as ONE lax.scan over time — the
    TPU-native replacement for warp-ctc's CUDA kernels. log_probs
    [T,B,C] (time-major, like paddle), labels [B,S] int, returns the
    negative log-likelihood per sample (reduced)."""
    log_probs = as_tensor(log_probs)
    labels_t = as_tensor(labels)
    in_len = as_tensor(input_lengths)
    lab_len = as_tensor(label_lengths)

    def fn(lp, lab, T_len, S_len):
        lp = jax.nn.log_softmax(lp, axis=-1)  # idempotent on log-probs
        T, B, C = lp.shape
        S = lab.shape[1]
        L = 2 * S + 1  # blank-interleaved target length
        NEG = -1e30

        # extended labels: blank, l1, blank, l2, ..., blank
        ext = jnp.full((B, L), blank, lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        # alpha recurrence allows skip (i-2) when ext[i] != ext[i-2]
        # and ext[i] != blank
        ext_prev2 = jnp.pad(ext, ((0, 0), (2, 0)),
                            constant_values=blank)[:, :L]
        can_skip = (ext != blank) & (ext != ext_prev2)

        def emit(t_lp, idx):
            return jnp.take_along_axis(t_lp, idx, axis=-1)

        alpha0 = jnp.full((B, L), NEG)
        alpha0 = alpha0.at[:, 0].set(emit(lp[0], ext[:, :1])[:, 0])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(S_len > 0, emit(lp[0], ext[:, 1:2])[:, 0], NEG))

        def step(alpha, t_lp):
            a_prev1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                              constant_values=NEG)[:, :L]
            a_prev2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                              constant_values=NEG)[:, :L]
            merged = jnp.logaddexp(alpha, a_prev1)
            merged = jnp.where(can_skip,
                               jnp.logaddexp(merged, a_prev2), merged)
            return merged + emit(t_lp, ext), None

        def body(carry, t):
            alpha, = carry
            new, _ = step(alpha, lp[t])
            # freeze past each sample's input length
            new = jnp.where((t < T_len)[:, None], new, alpha)
            return (new,), None

        (alpha,), _ = jax.lax.scan(body, (alpha0,),
                                   jnp.arange(1, T))
        # NLL = -log(alpha[last blank] + alpha[last label])
        last = 2 * S_len  # index of final blank
        aN = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
        aN1 = jnp.take_along_axis(
            alpha, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
        nll = -jnp.logaddexp(aN, jnp.where(S_len > 0, aN1, NEG))
        if norm_by_times:
            nll = nll / T_len.astype(nll.dtype)
        if reduction == "mean":
            # paddle normalizes each sample by its label length first
            return (nll / jnp.maximum(S_len, 1).astype(nll.dtype)).mean()
        return _reduce_loss(nll, reduction)

    return apply("ctc_loss", fn, log_probs, labels_t, in_len, lab_len)
