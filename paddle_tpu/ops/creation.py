"""Tensor creation ops — analog of python/paddle/tensor/creation.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import dtype as dtypes
from paddle_tpu.core.tensor import Tensor

__all__ = [
    "to_tensor",
    "zeros",
    "ones",
    "full",
    "zeros_like",
    "ones_like",
    "full_like",
    "empty",
    "empty_like",
    "arange",
    "linspace",
    "eye",
    "diag",
    "tril",
    "triu",
    "meshgrid",
    "one_hot",
    "logspace",
    "vander",
    "diagflat",
    "complex",
]


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


def _shape_tuple(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def zeros(shape, dtype=None) -> Tensor:
    return Tensor._wrap(jnp.zeros(_shape_tuple(shape), dtypes.to_jax(dtype)))


def ones(shape, dtype=None) -> Tensor:
    return Tensor._wrap(jnp.ones(_shape_tuple(shape), dtypes.to_jax(dtype)))


def full(shape, fill_value, dtype=None) -> Tensor:
    if dtype is None and isinstance(fill_value, (bool, int, float)):
        dtype = dtypes.infer_dtype(fill_value)
    return Tensor._wrap(jnp.full(_shape_tuple(shape), fill_value, dtypes.to_jax(dtype)))


def zeros_like(x, dtype=None) -> Tensor:
    return Tensor._wrap(jnp.zeros_like(x._array, dtype=dtypes.to_jax(dtype) if dtype else None))


def ones_like(x, dtype=None) -> Tensor:
    return Tensor._wrap(jnp.ones_like(x._array, dtype=dtypes.to_jax(dtype) if dtype else None))


def full_like(x, fill_value, dtype=None) -> Tensor:
    return Tensor._wrap(
        jnp.full_like(x._array, fill_value, dtype=dtypes.to_jax(dtype) if dtype else None)
    )


def empty(shape, dtype=None) -> Tensor:
    # XLA has no uninitialized memory; zeros compiles to a broadcast
    return zeros(shape, dtype)


def empty_like(x, dtype=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None) -> Tensor:
    if end is None:
        start, end = 0, start
    if dtype is None:
        if any(isinstance(v, float) for v in (start, end, step)):
            dtype = dtypes.get_default_dtype()
        else:
            dtype = "int64"
    return Tensor._wrap(jnp.arange(start, end, step, dtype=dtypes.to_jax(dtype)))


def linspace(start, stop, num, dtype=None) -> Tensor:
    return Tensor._wrap(jnp.linspace(start, stop, int(num), dtype=dtypes.to_jax(dtype)))


def eye(num_rows, num_columns=None, dtype=None) -> Tensor:
    return Tensor._wrap(jnp.eye(num_rows, num_columns, dtype=dtypes.to_jax(dtype)))


def diag(x, offset=0) -> Tensor:
    return Tensor._wrap(jnp.diag(x._array if isinstance(x, Tensor) else jnp.asarray(x), offset))


def tril(x, diagonal=0) -> Tensor:
    from .dispatch import apply

    return apply("tril", lambda a: jnp.tril(a, diagonal), x)


def triu(x, diagonal=0) -> Tensor:
    from .dispatch import apply

    return apply("triu", lambda a: jnp.triu(a, diagonal), x)


def meshgrid(*xs):
    arrays = [x._array if isinstance(x, Tensor) else jnp.asarray(x) for x in xs]
    return tuple(Tensor._wrap(a) for a in jnp.meshgrid(*arrays, indexing="ij"))


def one_hot(x, num_classes, dtype=None) -> Tensor:
    import jax.nn

    arr = x._array if isinstance(x, Tensor) else jnp.asarray(x)
    out = jax.nn.one_hot(arr, num_classes, dtype=dtypes.to_jax(dtype or dtypes.get_default_dtype()))
    return Tensor._wrap(out)


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    from paddle_tpu.core import dtype as dtypes

    jd = dtypes.to_jax(dtype) if dtype is not None else jnp.float32
    return Tensor._wrap(jnp.logspace(float(start), float(stop), int(num),
                                     base=float(base), dtype=jd))


def vander(x, n=None, increasing=False, name=None):
    from .dispatch import apply, as_tensor

    x = as_tensor(x)
    return apply("vander",
                 lambda a: jnp.vander(a, N=n, increasing=increasing), x)


def diagflat(x, offset=0, name=None):
    from .dispatch import apply, as_tensor

    x = as_tensor(x)
    return apply("diagflat", lambda a: jnp.diagflat(a, k=offset), x)


def complex(real, imag, name=None):
    """Build a complex tensor from real/imag parts (paddle.complex).
    On backends without complex buffers (core.device.supports_complex)
    the result lives CPU-side, like complex creation in Tensor()."""
    from .dispatch import apply, as_tensor

    r = as_tensor(real)
    i = as_tensor(imag, r)
    # float width follows the inputs (float64 → complex128 where x64 is
    # enabled), not a hard-coded float32
    fdt = jnp.promote_types(r._array.dtype, i._array.dtype)
    if not jnp.issubdtype(fdt, jnp.floating) or \
            jnp.finfo(fdt).bits < 32:
        # lax.complex accepts only f32/f64; sub-32-bit floats widen
        fdt = jnp.dtype(jnp.float32)

    def fn(a, b):
        a, b = jnp.broadcast_arrays(a.astype(fdt), b.astype(fdt))
        return jax.lax.complex(a, b)

    from paddle_tpu.core.device import supports_complex

    if not supports_complex() and \
            not isinstance(r._array, jax.core.Tracer):
        from .dispatch import apply_with_cpu_fallback

        # two-input op: pack both (broadcast) inputs ON the tape — the
        # pack is itself an apply() so gradients flow to r AND i through
        # the fallback path — then hop the packed array to CPU
        packed = apply(
            "complex_pack",
            lambda a, b: jnp.stack(
                jnp.broadcast_arrays(a.astype(fdt), b.astype(fdt))),
            r, i)
        return apply_with_cpu_fallback(
            apply, "complex", lambda p: jax.lax.complex(p[0], p[1]),
            packed, supports_complex, complex_stays_on_cpu=True)
    return apply("complex", fn, r, i)
