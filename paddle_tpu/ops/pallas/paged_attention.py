"""Fused TPU paged-attention DECODE kernel — the kernel PR 1's
`ops/paged_attention.py` left a seam for.

One batched decode step against the vLLM-style paged KV pool
(`[layers, num_blocks, block_size, heads, head_dim]`, block 0 = null):
the grid runs one program per decode slot, and each program

- writes the incoming token's k/v row into the pool at
  `(block_table[pos // bs], pos % bs)` (fused KV write: the pool is an
  input/output-aliased operand, so the write is an in-place DMA, not a
  functional copy of the pool);
- walks the slot's block table and STREAMS only the blocks at or below
  its position from HBM into a double-buffered VMEM scratch
  (`make_async_copy`, next block's DMA in flight behind the current
  block's compute) — O(active context) HBM traffic per slot per step,
  where the dense fallback pays O(high-water) and the PR-1 gather paid
  O(max_model_len);
- accumulates FlashAttention-style online softmax in fp32 across the
  streamed blocks and normalizes once at the end.

Null-block semantics are preserved: an idle slot (position 0, all-null
table) writes its garbage row into block 0 and attends only position 0
— a one-element softmax, finite by construction — and live slots never
read a trailing-zero table entry because the walk stops at
`pos // block_size`.

`paged_verify_attention` is the speculative-decoding sibling (PR 7):
the same per-slot grid, block-table walk, and fused-write machinery,
widened from one query per slot to a fixed `[W = K+1]` token window.
Each program fires W write DMAs (live window rows through the table,
dead rows to the null block) before the walk, waits for ALL of them
just before the first block the window writes into is streamed (blocks
below the feed position are write-independent and stream concurrently
with the writes), and carries the online-softmax state per window row
— so a verify step's HBM traffic is one context walk amortized over
K+1 scored positions, which is the whole speculative-decoding win.

Interpret mode (`interpret=True`) runs the same kernels through the
Pallas interpreter, which is how CPU CI tests them token-exactly
against the dense path; the op-tier seam (`ops/paged_attention.py`)
forces interpret whenever no TPU is attached.

Tensor-parallel serving (PR 8): the kernels read `heads` from the
operand shapes, never from model config, so the sharded engine invokes
them PER SHARD inside shard_map with heads/mp-head pools and
projections — one grid program per slot per shard, each walking the
same replicated block table over its own pool plane. No cross-shard
communication exists at this level (attention is per-head); the
interpreter path composes with shard_map the same way, which is how
the virtual-mesh CPU CI proves the sharded kernel token-exact.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["paged_decode_attention", "paged_verify_attention"]

_NEG_INF = -1e30


def _decode_kernel(bt_ref, pos_ref, q_ref, knew_ref, vnew_ref,
                   kpool_in, vpool_in, o_ref, kpool_ref, vpool_ref,
                   kbuf, vbuf, copy_sems, write_sems, *,
                   layer, block_size, scale):
    """One program per slot. bt_ref [slots, max_blocks] and pos_ref
    [slots] are scalar-prefetch (SMEM) so DMA indices are computable
    before the body runs. kpool_ref/vpool_ref are the ALIASED output
    refs of the full pools (ANY/HBM memory space); kpool_in/vpool_in
    are the same buffers' input refs and are intentionally unused.
    kbuf/vbuf are [2, block_size, heads, D] VMEM double buffers."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s = pl.program_id(0)
    pos = pos_ref[s]
    last_blk = pos // block_size
    nblk = last_blk + 1

    # fused KV write: this token's row lands in the pool before the
    # LAST block of this slot's walk is streamed (that block reads it
    # back); earlier blocks don't depend on it, so their copies run
    # concurrently with the write instead of behind a write round-trip
    wk = pltpu.make_async_copy(
        knew_ref.at[0],
        kpool_ref.at[layer, bt_ref[s, last_blk], pos % block_size],
        write_sems.at[0])
    wv = pltpu.make_async_copy(
        vnew_ref.at[0],
        vpool_ref.at[layer, bt_ref[s, last_blk], pos % block_size],
        write_sems.at[1])
    wk.start()
    wv.start()

    def kv_copies(j, buf):
        bid = bt_ref[s, j]
        return (pltpu.make_async_copy(kpool_ref.at[layer, bid],
                                      kbuf.at[buf], copy_sems.at[0, buf]),
                pltpu.make_async_copy(vpool_ref.at[layer, bid],
                                      vbuf.at[buf], copy_sems.at[1, buf]))

    def start_copies(j, buf):
        ck, cv = kv_copies(j, buf)
        ck.start()
        cv.start()

    @pl.when(last_blk == 0)
    def _first_is_last():           # 1-block walk: copy needs the write
        wk.wait()
        wv.wait()
        start_copies(0, 0)

    @pl.when(last_blk > 0)
    def _first():                   # block 0 is write-independent
        start_copies(0, 0)

    # inputs stay at the pool dtype through the matmuls (bf16 MXU
    # passes on TPU); accumulation is forced fp32 by
    # preferred_element_type — same numerics policy as the dense path
    q = q_ref[0].astype(kbuf.dtype)             # [heads, D]
    heads, head_dim = q.shape

    def body(j, carry):
        m, l, acc = carry

        @pl.when(j + 1 < nblk)
        def _prefetch():
            @pl.when(j + 1 == last_blk)
            def _writes_land_first():   # exactly once per program
                wk.wait()
                wv.wait()

            start_copies(j + 1, (j + 1) % 2)

        ck, cv = kv_copies(j, j % 2)
        ck.wait()
        cv.wait()
        k = kbuf[j % 2]                         # [bs, heads, D]
        v = vbuf[j % 2]
        sc = jnp.einsum("hd,khd->hk", q, k,
                        preferred_element_type=jnp.float32) * scale
        gpos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (heads, block_size), 1)
        sc = jnp.where(gpos <= pos, sc, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sc, axis=-1, keepdims=True))
        p = jnp.exp(sc - m_new)                 # [heads, bs] fp32
        alpha = jnp.exp(m - m_new)              # [heads, 1]
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "hk,khd->hd", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((heads, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((heads, 1), jnp.float32)
    acc0 = jnp.zeros((heads, head_dim), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, nblk, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _decode_kernel_int8(bt_ref, pos_ref, sref, q_ref, knew_ref,
                        vnew_ref, kpool_in, vpool_in, o_ref, kpool_ref,
                        vpool_ref, kbuf, vbuf, copy_sems, write_sems,
                        *, layer, block_size, scale):
    """int8 edition of `_decode_kernel`: the pools hold int8 codes and
    `sref` is this LAYER's per-block `[num_blocks, 2]` K/V scale plane,
    scalar-prefetched with the block tables. knew/vnew arrive ALREADY
    quantized (the op seam runs quant-on-write: grid grow + requantize
    + scale update happen before the kernel, so the fused write DMA
    below lands the final int8 bytes). Dequant is fused into the
    streamed-block matmuls — int8 codes cast to f32 once in VMEM and
    each block's logits/PV scaled by ITS grid — with the fp32 online
    softmax unchanged; the operation order mirrors `_dense_step_q`
    exactly so both backends agree token-for-token."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s = pl.program_id(0)
    pos = pos_ref[s]
    last_blk = pos // block_size
    nblk = last_blk + 1

    wk = pltpu.make_async_copy(
        knew_ref.at[0],
        kpool_ref.at[layer, bt_ref[s, last_blk], pos % block_size],
        write_sems.at[0])
    wv = pltpu.make_async_copy(
        vnew_ref.at[0],
        vpool_ref.at[layer, bt_ref[s, last_blk], pos % block_size],
        write_sems.at[1])
    wk.start()
    wv.start()

    def kv_copies(j, buf):
        bid = bt_ref[s, j]
        return (pltpu.make_async_copy(kpool_ref.at[layer, bid],
                                      kbuf.at[buf], copy_sems.at[0, buf]),
                pltpu.make_async_copy(vpool_ref.at[layer, bid],
                                      vbuf.at[buf], copy_sems.at[1, buf]))

    def start_copies(j, buf):
        ck, cv = kv_copies(j, buf)
        ck.start()
        cv.start()

    @pl.when(last_blk == 0)
    def _first_is_last():           # 1-block walk: copy needs the write
        wk.wait()
        wv.wait()
        start_copies(0, 0)

    @pl.when(last_blk > 0)
    def _first():                   # block 0 is write-independent
        start_copies(0, 0)

    q = q_ref[0].astype(jnp.float32)            # [heads, D]
    heads, head_dim = q.shape

    def body(j, carry):
        m, l, acc = carry

        @pl.when(j + 1 < nblk)
        def _prefetch():
            @pl.when(j + 1 == last_blk)
            def _writes_land_first():   # exactly once per program
                wk.wait()
                wv.wait()

            start_copies(j + 1, (j + 1) % 2)

        ck, cv = kv_copies(j, j % 2)
        ck.wait()
        cv.wait()
        bid = bt_ref[s, j]
        ks, vs = sref[bid, 0], sref[bid, 1]     # this block's grid
        k = kbuf[j % 2].astype(jnp.float32)     # [bs, heads, D]
        v = vbuf[j % 2].astype(jnp.float32)
        sc = jnp.einsum("hd,khd->hk", q, k,
                        preferred_element_type=jnp.float32) * scale
        sc = sc * ks                            # fused dequant (K)
        gpos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (heads, block_size), 1)
        sc = jnp.where(gpos <= pos, sc, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sc, axis=-1, keepdims=True))
        p = jnp.exp(sc - m_new)                 # [heads, bs] fp32
        alpha = jnp.exp(m - m_new)              # [heads, 1]
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("hk,khd->hd", p, v,
                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc * alpha + pv * vs

    m0 = jnp.full((heads, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((heads, 1), jnp.float32)
    acc0 = jnp.zeros((heads, head_dim), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, nblk, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def paged_decode_attention(q, knew, vnew, kpool, vpool, layer,
                           block_tables, positions, scale=None,
                           interpret: bool = False, kv_scales=None):
    """Fused paged decode attention over the global pool, one layer.

    q/knew/vnew: `[slots, 1, heads, head_dim]` — this step's
    projections. kpool/vpool: `[layers, num_blocks, block_size, heads,
    head_dim]`. layer: python int (static). block_tables
    `[slots, max_blocks]` int32; positions `[slots]` int32.

    `kv_scales` switches on the int8 path: the pools are int8 codes,
    knew/vnew arrive ALREADY quantized by the op seam, and `kv_scales`
    is this layer's `[num_blocks, 2]` per-block K/V grid, ridden as a
    third scalar-prefetch operand and fused into the streamed-block
    matmuls.

    Returns `(out [slots, 1, heads, head_dim], new_kpool, new_vpool)`
    with the pools updated in place when XLA can alias them (the
    engine's donated decode step) — same contract as the dense
    `paged_attention_step` fallback.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    slots, one, heads, head_dim = q.shape
    assert one == 1, "decode kernel takes one token per slot"
    num_layers, num_blocks, block_size, _, _ = kpool.shape
    if scale is None:
        scale = 1.0 / (head_dim ** 0.5)

    q3 = q.reshape(slots, heads, head_dim)
    k3 = knew.reshape(slots, heads, head_dim).astype(kpool.dtype)
    v3 = vnew.reshape(slots, heads, head_dim).astype(vpool.dtype)

    if kv_scales is not None:
        kernel = functools.partial(_decode_kernel_int8,
                                   layer=int(layer),
                                   block_size=block_size, scale=scale)
        prefetch = (block_tables.astype(jnp.int32),
                    positions.astype(jnp.int32),
                    kv_scales.astype(jnp.float32))
    else:
        kernel = functools.partial(_decode_kernel, layer=int(layer),
                                   block_size=block_size, scale=scale)
        prefetch = (block_tables.astype(jnp.int32),
                    positions.astype(jnp.int32))
    row = lambda s, *_: (s, 0, 0)  # noqa: E731 — per-slot [1,heads,D]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),  # tables, positions[, scales]
        grid=(slots,),
        in_specs=[
            pl.BlockSpec((1, heads, head_dim), row),
            pl.BlockSpec((1, heads, head_dim), row),
            pl.BlockSpec((1, heads, head_dim), row),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, heads, head_dim), row),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, block_size, heads, head_dim), kpool.dtype),
            pltpu.VMEM((2, block_size, heads, head_dim), vpool.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),   # [k|v, buffer]
            pltpu.SemaphoreType.DMA((2,)),     # [k|v] fused write
        ],
    )
    out, new_kpool, new_vpool = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((slots, heads, head_dim), q.dtype),
            jax.ShapeDtypeStruct(kpool.shape, kpool.dtype),
            jax.ShapeDtypeStruct(vpool.shape, vpool.dtype),
        ],
        # flat input order: bt, pos[, scales], q, knew, vnew, kpool,
        # vpool — the pools alias outputs 1/2 so the fused write
        # mutates in place
        input_output_aliases={len(prefetch) + 3: 1,
                              len(prefetch) + 4: 2},
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(*prefetch, q3, k3, v3, kpool, vpool)
    return out.reshape(slots, 1, heads, head_dim), new_kpool, new_vpool


def _verify_kernel(bt_ref, pos_ref, dlen_ref, q_ref, knew_ref, vnew_ref,
                   kpool_in, vpool_in, o_ref, kpool_ref, vpool_ref,
                   kbuf, vbuf, copy_sems, write_sems, *,
                   layer, block_size, scale, max_blocks):
    """One program per slot, W = K+1 window rows. bt_ref
    [slots, max_blocks], pos_ref [slots] (row-0 absolute position) and
    dlen_ref [slots] (live rows = 0..dlen) are scalar-prefetch (SMEM).
    q/knew/vnew refs are `[1, W, heads, D]` per-slot blocks;
    write_sems is `[2, W]` (one k/v DMA pair per window row)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s = pl.program_id(0)
    pos = pos_ref[s]
    dlen = dlen_ref[s]
    W = q_ref.shape[1]                  # static window width
    first_wb = pos // block_size        # first block the window writes
    last_blk = (pos + dlen) // block_size
    nblk = last_blk + 1

    # fused KV writes, one DMA pair per window row: live rows land
    # through the table (the engine pre-promoted every touched block to
    # private ownership), dead rows (i > dlen) land in the null block 0
    writes = []
    for i in range(W):
        wpos = pos + i
        live = i <= dlen
        bid = jnp.where(
            live,
            bt_ref[s, jnp.minimum(wpos // block_size, max_blocks - 1)],
            0)
        off = wpos % block_size
        wk = pltpu.make_async_copy(knew_ref.at[0, i],
                                   kpool_ref.at[layer, bid, off],
                                   write_sems.at[0, i])
        wv = pltpu.make_async_copy(vnew_ref.at[0, i],
                                   vpool_ref.at[layer, bid, off],
                                   write_sems.at[1, i])
        wk.start()
        wv.start()
        writes.append((wk, wv))

    def wait_writes():
        for wk, wv in writes:
            wk.wait()
            wv.wait()

    def kv_copies(j, buf):
        bid = bt_ref[s, j]
        return (pltpu.make_async_copy(kpool_ref.at[layer, bid],
                                      kbuf.at[buf], copy_sems.at[0, buf]),
                pltpu.make_async_copy(vpool_ref.at[layer, bid],
                                      vbuf.at[buf], copy_sems.at[1, buf]))

    def start_copies(j, buf):
        ck, cv = kv_copies(j, buf)
        ck.start()
        cv.start()

    @pl.when(first_wb == 0)
    def _writes_cover_first():      # window touches block 0: land first
        wait_writes()
        start_copies(0, 0)

    @pl.when(first_wb > 0)
    def _first():                   # block 0 is write-independent
        start_copies(0, 0)

    # inputs stay at the pool dtype through the matmuls; accumulation
    # is forced fp32 — the same policy as decode and the dense paths
    q = q_ref[0].astype(kbuf.dtype)             # [W, heads, D]
    _, heads, head_dim = q.shape

    def body(j, carry):
        m, l, acc = carry

        @pl.when(j + 1 < nblk)
        def _prefetch():
            @pl.when(j + 1 == first_wb)
            def _writes_land_first():   # at most once per program
                wait_writes()

            start_copies(j + 1, (j + 1) % 2)

        ck, cv = kv_copies(j, j % 2)
        ck.wait()
        cv.wait()
        k = kbuf[j % 2]                         # [bs, heads, D]
        v = vbuf[j % 2]
        sc = jnp.einsum("whd,khd->hwk", q, k,
                        preferred_element_type=jnp.float32) * scale
        # causal per window row over absolute positions
        kpos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (W, block_size), 1)
        qpos = pos + jax.lax.broadcasted_iota(
            jnp.int32, (W, block_size), 0)
        sc = jnp.where((kpos <= qpos)[None], sc, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sc, axis=-1, keepdims=True))
        p = jnp.exp(sc - m_new)                 # [heads, W, bs] fp32
        alpha = jnp.exp(m - m_new)              # [heads, W, 1]
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "hwk,khd->hwd", p.astype(v.dtype), v,
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((heads, W, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((heads, W, 1), jnp.float32)
    acc0 = jnp.zeros((heads, W, head_dim), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, nblk, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)) \
        .transpose(1, 0, 2).astype(o_ref.dtype)


def _verify_kernel_int8(bt_ref, pos_ref, dlen_ref, sref, q_ref,
                        knew_ref, vnew_ref, kpool_in, vpool_in, o_ref,
                        kpool_ref, vpool_ref, kbuf, vbuf, copy_sems,
                        write_sems, *, layer, block_size, scale,
                        max_blocks):
    """int8 edition of `_verify_kernel`: `sref` is this layer's
    per-block `[num_blocks, 2]` K/V grid (4th scalar-prefetch operand)
    and knew/vnew arrive already quantized by the op seam's window
    quant-on-write. Same write/stream choreography; dequant fused into
    the streamed-block matmuls in `_dense_verify_q`'s exact operation
    order."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s = pl.program_id(0)
    pos = pos_ref[s]
    dlen = dlen_ref[s]
    W = q_ref.shape[1]                  # static window width
    first_wb = pos // block_size        # first block the window writes
    last_blk = (pos + dlen) // block_size
    nblk = last_blk + 1

    writes = []
    for i in range(W):
        wpos = pos + i
        live = i <= dlen
        bid = jnp.where(
            live,
            bt_ref[s, jnp.minimum(wpos // block_size, max_blocks - 1)],
            0)
        off = wpos % block_size
        wk = pltpu.make_async_copy(knew_ref.at[0, i],
                                   kpool_ref.at[layer, bid, off],
                                   write_sems.at[0, i])
        wv = pltpu.make_async_copy(vnew_ref.at[0, i],
                                   vpool_ref.at[layer, bid, off],
                                   write_sems.at[1, i])
        wk.start()
        wv.start()
        writes.append((wk, wv))

    def wait_writes():
        for wk, wv in writes:
            wk.wait()
            wv.wait()

    def kv_copies(j, buf):
        bid = bt_ref[s, j]
        return (pltpu.make_async_copy(kpool_ref.at[layer, bid],
                                      kbuf.at[buf], copy_sems.at[0, buf]),
                pltpu.make_async_copy(vpool_ref.at[layer, bid],
                                      vbuf.at[buf], copy_sems.at[1, buf]))

    def start_copies(j, buf):
        ck, cv = kv_copies(j, buf)
        ck.start()
        cv.start()

    @pl.when(first_wb == 0)
    def _writes_cover_first():      # window touches block 0: land first
        wait_writes()
        start_copies(0, 0)

    @pl.when(first_wb > 0)
    def _first():                   # block 0 is write-independent
        start_copies(0, 0)

    q = q_ref[0].astype(jnp.float32)            # [W, heads, D]
    _, heads, head_dim = q.shape

    def body(j, carry):
        m, l, acc = carry

        @pl.when(j + 1 < nblk)
        def _prefetch():
            @pl.when(j + 1 == first_wb)
            def _writes_land_first():   # at most once per program
                wait_writes()

            start_copies(j + 1, (j + 1) % 2)

        ck, cv = kv_copies(j, j % 2)
        ck.wait()
        cv.wait()
        bid = bt_ref[s, j]
        ks, vs = sref[bid, 0], sref[bid, 1]     # this block's grid
        k = kbuf[j % 2].astype(jnp.float32)     # [bs, heads, D]
        v = vbuf[j % 2].astype(jnp.float32)
        sc = jnp.einsum("whd,khd->hwk", q, k,
                        preferred_element_type=jnp.float32) * scale
        sc = sc * ks                            # fused dequant (K)
        kpos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (W, block_size), 1)
        qpos = pos + jax.lax.broadcasted_iota(
            jnp.int32, (W, block_size), 0)
        sc = jnp.where((kpos <= qpos)[None], sc, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sc, axis=-1, keepdims=True))
        p = jnp.exp(sc - m_new)                 # [heads, W, bs] fp32
        alpha = jnp.exp(m - m_new)              # [heads, W, 1]
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("hwk,khd->hwd", p, v,
                        preferred_element_type=jnp.float32)
        return m_new, l_new, acc * alpha + pv * vs

    m0 = jnp.full((heads, W, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((heads, W, 1), jnp.float32)
    acc0 = jnp.zeros((heads, W, head_dim), jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, nblk, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)) \
        .transpose(1, 0, 2).astype(o_ref.dtype)


def paged_verify_attention(q, knew, vnew, kpool, vpool, layer,
                           block_tables, positions, draft_lens,
                           scale=None, interpret: bool = False,
                           kv_scales=None):
    """Fused speculative-verify attention over the global pool, one
    layer.

    q/knew/vnew: `[slots, W, heads, head_dim]` — the K-token verify
    window's projections (W = K+1). kpool/vpool:
    `[layers, num_blocks, block_size, heads, head_dim]`. layer: python
    int (static). block_tables `[slots, max_blocks]` int32; positions
    `[slots]` int32 (window row 0's absolute position); draft_lens
    `[slots]` int32 — rows past a slot's draft length write the null
    block and produce garbage the engine discards.

    Returns `(out [slots, W, heads, head_dim], new_kpool, new_vpool)`
    with the pools updated in place when XLA can alias them — the same
    contract as `paged_decode_attention`."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    slots, W, heads, head_dim = q.shape
    assert W >= 2, "verify window needs at least one draft row (W >= 2)"
    num_layers, num_blocks, block_size, _, _ = kpool.shape
    max_blocks = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / (head_dim ** 0.5)

    k4 = knew.astype(kpool.dtype)
    v4 = vnew.astype(vpool.dtype)

    if kv_scales is not None:
        kernel = functools.partial(_verify_kernel_int8,
                                   layer=int(layer),
                                   block_size=block_size, scale=scale,
                                   max_blocks=max_blocks)
        prefetch = (block_tables.astype(jnp.int32),
                    positions.astype(jnp.int32),
                    draft_lens.astype(jnp.int32),
                    kv_scales.astype(jnp.float32))
    else:
        kernel = functools.partial(_verify_kernel, layer=int(layer),
                                   block_size=block_size, scale=scale,
                                   max_blocks=max_blocks)
        prefetch = (block_tables.astype(jnp.int32),
                    positions.astype(jnp.int32),
                    draft_lens.astype(jnp.int32))
    row = lambda s, *_: (s, 0, 0, 0)  # noqa: E731 — [1, W, heads, D]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),  # bt, pos, dlen[, scales]
        grid=(slots,),
        in_specs=[
            pl.BlockSpec((1, W, heads, head_dim), row),
            pl.BlockSpec((1, W, heads, head_dim), row),
            pl.BlockSpec((1, W, heads, head_dim), row),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, W, heads, head_dim), row),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, block_size, heads, head_dim), kpool.dtype),
            pltpu.VMEM((2, block_size, heads, head_dim), vpool.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),   # [k|v, stream buffer]
            pltpu.SemaphoreType.DMA((2, W)),   # [k|v, window row] write
        ],
    )
    out, new_kpool, new_vpool = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((slots, W, heads, head_dim), q.dtype),
            jax.ShapeDtypeStruct(kpool.shape, kpool.dtype),
            jax.ShapeDtypeStruct(vpool.shape, vpool.dtype),
        ],
        # flat input order: bt, pos, dlen[, scales], q, knew, vnew,
        # kpool, vpool — the pools alias outputs 1/2 so writes mutate
        # in place
        input_output_aliases={len(prefetch) + 3: 1,
                              len(prefetch) + 4: 2},
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(*prefetch, q, k4, v4, kpool, vpool)
    return out, new_kpool, new_vpool
