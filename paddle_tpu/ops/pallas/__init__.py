"""Pallas TPU kernels — the analog of the reference's hand-written fused
CUDA ops (paddle/fluid/operators/fused/): where XLA's automatic fusion
isn't enough (flash attention, paged-attention decode, conv+BN+ReLU),
we drop to Pallas.
"""
from .conv import fused_conv_bn_relu, resolve_conv_backend
from .flash_attention import flash_attention, pallas_sdpa_forward
from .paged_attention import paged_decode_attention

__all__ = ["flash_attention", "pallas_sdpa_forward",
           "paged_decode_attention", "fused_conv_bn_relu",
           "resolve_conv_backend"]
