"""Pallas TPU kernels — the analog of the reference's hand-written fused
CUDA ops (paddle/fluid/operators/fused/): where XLA's automatic fusion
isn't enough (flash attention, MoE block matmuls), we drop to Pallas.
"""
from .flash_attention import flash_attention, pallas_sdpa_forward
from .paged_attention import paged_decode_attention

__all__ = ["flash_attention", "pallas_sdpa_forward",
           "paged_decode_attention"]
