"""Fused conv+BN+ReLU Pallas kernels — the custom conv suite the
ResNet-50 MFU plateau calls for (ROADMAP item 5, DESIGN_DECISIONS r17).

BENCH_r05 and the `conv_c2_*`/`conv_c5_*` sweep in bench_ops.py put
numbers on the problem: the stage-1/2 ResNet shapes run at 24-76
TFLOP/s through `lax.conv_general_dilated` against 184 TFLOP/s for a
same-FLOP matmul, and the r5 fusion probe showed even perfect XLA
conv+BN fusion caps at ~0.20 MFU — the early stages are ~90%
bandwidth-bound on activation re-reads between conv, BN and ReLU.
These kernels attack exactly that traffic: ONE HBM read of the
activation, the conv as explicit MXU matmuls with fp32 accumulation,
and the BatchNorm scale/shift + ReLU applied in-register before the
single HBM write-back.

Two kernel families cover the ResNet bottleneck sweep:

- 1x1 convs (`_conv1x1_kernel`): a 1x1 conv IS a matmul — the input is
  viewed as `[N*Ho*Wo, Cin]`, tiled over rows, and each grid program
  runs one `[TM, Cin] x [Cin, Cout]` MXU pass with the epilogue fused.
  This alone targets `conv_c2_1x1_64_256` and `conv_c5_1x1_512_2048`,
  the worst matmul-gap rows of the sweep. Stride-2 1x1 (the downsample
  path) pre-slices the input — exact, and the slice is 1/4 the read.
- 3x3 stride-1/2 convs (`_conv3x3_kernel`): implicit GEMM. One grid
  program per image streams output-row slabs of the (pre-padded) input
  HBM->VMEM through a double-buffered scratch — the next slab's DMA in
  flight behind the current slab's compute, halo rows riding inside
  each slab — and computes the conv as 9 shifted `[TH*Wo, Cin] x
  [Cin, Cout]` tap matmuls accumulated in fp32
  (`preferred_element_type`; tpu-verify TPU103 pins it), epilogue
  fused, one output write.

Padding is materialized once with `jnp.pad` before the 3x3 kernel (a
single fused memset+copy) so every slab DMA is in-bounds with a static
shape; the win this suite claims is eliminating the BN/ReLU activation
round-trips, which dwarf the one-off pad. Both `"SAME"` (the bench
sweep's convention — asymmetric at stride 2) and paddle's explicit
symmetric padding (the ResNet blocks' convention) resolve to the same
VALID-over-padded-input geometry, so one kernel serves both.

Backend seam — the `ops/paged_attention.py` pattern verbatim:
`resolve_conv_backend` maps `auto`/`dense`/`pallas` (env override
`PADDLE_CONV_BACKEND` wins, resolved ONCE at block construction by
`nn/fused.py`); `auto` picks the fused kernel only on TPU at supported
shapes; explicit `pallas` off-TPU runs the interpreter (the CPU CI
path, tested numerically against the dense composition like the
paged-attention kernels); unsupported shapes — the 7x7/s2 stem,
grouped/dilated convs, ragged channel counts — fall back to `dense`
CLEANLY whatever was requested, and `CONV_PATH_STATS` records every
dispatch so a silent fallback is impossible (flash_attention
PATH_STATS precedent).

The fused path is a FORWARD (inference/eval) op: training keeps the
differentiable dense composition (`nn/fused.py` routes by mode), and
the dense foil is also the exactness reference for every test and
bench row. TraceContracts for both kernel families are declared here,
colocated with the builders, and `harvest_programs()` hands tpu-verify
tiny-but-real jitted instances so their lowering is gated like every
other compiled program.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from paddle_tpu.analysis.trace.contracts import TraceContract, \
    register_contract

__all__ = ["fused_conv_bn_relu", "conv_bn_relu_reference",
           "resolve_conv_backend", "conv_shapes_supported",
           "conv_geometry_tileable", "normalize_conv_padding",
           "CONV_BACKENDS", "CONV_PATH_STATS",
           "reset_conv_path_stats", "harvest_programs",
           "CONV_HARVEST_SHAPES"]

CONV_BACKENDS = ("auto", "dense", "pallas")

# which backend a fused-conv dispatch actually ran, incremented per
# call (per TRACE under jit). Tests read it to prove the requested
# kernel engaged / the stem fell back — never a silent fallback.
CONV_PATH_STATS = {"dense": 0, "pallas": 0}


def reset_conv_path_stats():
    CONV_PATH_STATS["dense"] = 0
    CONV_PATH_STATS["pallas"] = 0


def _on_tpu():
    try:
        return jax.devices()[0].platform == "tpu" or \
            jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _pair(v=1):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 2


def normalize_conv_padding(padding=0, kernel=3, stride=1, in_hw=None):
    """Paddle/lax padding spec -> ((top, bottom), (left, right)).

    Accepts an int, a 2-int per-dim pad, 2 (lo, hi) pairs, or the
    "SAME"/"VALID" strings. "SAME" needs `in_hw` because lax pads it
    asymmetrically at stride > 1 (total = (ceil(d/s)-1)*s + k - d, lo =
    total//2) — the bench sweep's convention, distinct from the ResNet
    blocks' symmetric padding=1."""
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride)
    if isinstance(padding, str):
        p = padding.upper()
        if p == "VALID":
            return ((0, 0), (0, 0))
        if p == "SAME":
            if in_hw is None:
                raise ValueError("SAME padding needs the input H/W")
            out = []
            for d, k, s in zip(in_hw, (kh, kw), (sh, sw)):
                total = max((-(-d // s) - 1) * s + k - d, 0)
                out.append((total // 2, total - total // 2))
            return tuple(out)
        raise ValueError(f"unsupported conv padding {padding!r}")
    if isinstance(padding, (list, tuple)):
        if len(padding) == 2 and all(
                isinstance(p, (list, tuple)) for p in padding):
            return tuple((int(lo), int(hi)) for lo, hi in padding)
        if len(padding) == 2:
            return tuple((int(p), int(p)) for p in padding)
        if len(padding) == 4:
            return ((int(padding[0]), int(padding[1])),
                    (int(padding[2]), int(padding[3])))
        raise ValueError(f"unsupported conv padding {padding!r}")
    p = int(padding)
    return ((p, p), (p, p))


def conv_shapes_supported(kernel=3, stride=1, in_channels=8,
                          out_channels=8, dilation=1, groups=1,
                          padding=0):
    """Static-shape gate for the fused kernels: k in {1, 3} square,
    stride in {1, 2} square, no dilation/groups, channel counts in
    multiples of 8 (sublane-friendly tiles), and zero padding for the
    1x1 family (a padded 1x1 conv is not a matmul). Everything else —
    the 7x7/s2 stem above all — runs the dense composition; callers
    resolve ONCE so the answer never flips mid-serving."""
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride)
    dh, dw = _pair(dilation)
    if (kh, kw) not in ((1, 1), (3, 3)) or kh != kw:
        return False
    if sh != sw or sh not in (1, 2):
        return False
    if dh != 1 or dw != 1 or groups != 1:
        return False
    if in_channels % 8 or out_channels % 8:
        return False
    if (kh, kw) == (1, 1) and not isinstance(padding, str):
        pads = normalize_conv_padding(padding, kernel, stride,
                                      in_hw=(8, 8))
        if any(p != (0, 0) for p in pads):
            return False
    return True


def conv_geometry_tileable(kernel=3, stride=1, padding=0, in_hw=None):
    """Per-call geometry gate for the 3x3 family — the H/W-dependent
    half `conv_shapes_supported` (static, construction-time) cannot
    see: True when the output rows tile within the kernel's unroll
    bound and every slab DMA lands in-bounds of the padded input.
    1x1 geometries always tile (the row-tile pad covers any M).
    `nn/fused.py` checks this per forward and runs the dense
    composition when it fails — the same clean-fallback contract as
    the static gate, just resolved at the first shape-bearing call."""
    kh, kw = _pair(kernel)
    if (kh, kw) == (1, 1):
        return True
    sh, _ = _pair(stride)
    pads = normalize_conv_padding(padding, kernel, stride, in_hw=in_hw)
    (pt, pb) = pads[0]
    hp = int(in_hw[0]) + pt + pb
    ho = (hp - 3) // sh + 1
    wo = (int(in_hw[1]) + sum(pads[1]) - 3) // sh + 1
    if ho < 1 or wo < 1:
        return False
    th = _pick_h_tile(ho)
    num_tiles = ho // th
    if num_tiles > 16:                        # unroll-depth bound
        return False
    slab = sh * (th - 1) + 3
    return sh * (num_tiles - 1) * th + slab <= hp


def resolve_conv_backend(backend=None, *, kernel=(3, 3), stride=(1, 1),
                         in_channels=8, out_channels=8, dilation=1,
                         groups=1, padding=0):
    """Resolve `auto`/`dense`/`pallas` to the backend a fused conv
    block will run — ONCE, at construction (the paged-attention
    `resolve_backend` pattern). The `PADDLE_CONV_BACKEND` env override
    wins over the constructor argument (deploy semantics). Unsupported
    static shapes resolve `dense` whatever was requested — the clean
    fallback the 7x7 stem rides — while a supported shape honours an
    explicit `dense`/`pallas` (off-TPU, `pallas` runs the interpreter:
    the CPU CI path); `auto` picks the fused kernel only on TPU."""
    requested = os.environ.get("PADDLE_CONV_BACKEND") or backend \
        or "auto"
    if requested not in CONV_BACKENDS:
        raise ValueError(f"conv backend must be one of {CONV_BACKENDS}, "
                         f"got {requested!r}")
    if not conv_shapes_supported(kernel, stride, in_channels,
                                 out_channels, dilation, groups,
                                 padding):
        return "dense"
    if requested != "auto":
        return requested
    return "pallas" if _on_tpu() else "dense"


# ---------------------------------------------------------------------------
# dense reference (the exactness foil)
# ---------------------------------------------------------------------------

def conv_bn_relu_reference(x, w, scale, shift, stride=1, padding=0,
                           relu=True):
    """The dense `lax.conv_general_dilated` composition the fused
    kernels are tested and benched against: conv with fp32
    accumulation, BN scale/shift in fp32, optional ReLU, ONE cast back
    to the input dtype. x `[N, H, W, Cin]`, w `[kh, kw, Cin, Cout]`,
    scale/shift `[Cout]` f32 (the folded BatchNorm affine)."""
    sh, sw = _pair(stride)
    pads = normalize_conv_padding(padding, w.shape[:2], stride,
                                  in_hw=x.shape[1:3])
    out = jax.lax.conv_general_dilated(
        x, w, (sh, sw), list(pads),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    out = out * scale.astype(jnp.float32) + shift.astype(jnp.float32)
    if relu:
        out = jnp.maximum(out, 0.0)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# 1x1 family: the conv IS a matmul
# ---------------------------------------------------------------------------

def _conv1x1_kernel(x_ref, w_ref, scale_ref, shift_ref, o_ref, *, relu):
    """One `[TM, Cin] x [Cin, Cout]` MXU pass, epilogue in-register:
    fp32 accumulation, BN scale/shift, optional ReLU, one cast."""
    acc = jax.lax.dot_general(
        x_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    y = acc * scale_ref[...] + shift_ref[...]      # [TM,Cout]*[1,Cout]
    if relu:
        y = jnp.maximum(y, 0.0)
    o_ref[...] = y.astype(o_ref.dtype)


def _pick_row_tile(m=8):
    """Row-tile for the 1x1 matmul: a power-of-two divisor keeps every
    grid step identical; otherwise the wrapper zero-pads M up to the
    tile (the pad rows are sliced off after — ~one tile of waste)."""
    for tm in (512, 256, 128):
        if m % tm == 0:
            return tm
    return 128 if m >= 128 else 8


def _conv1x1_call(x2, w2, scale, shift, relu, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, Cin = x2.shape
    Cout = w2.shape[1]
    TM = _pick_row_tile(M)
    pad = (-M) % TM
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        functools.partial(_conv1x1_kernel, relu=relu),
        grid=((M + pad) // TM,),
        in_specs=[
            pl.BlockSpec((TM, Cin), lambda i: (i, 0)),
            pl.BlockSpec((Cin, Cout), lambda i: (0, 0)),
            pl.BlockSpec((1, Cout), lambda i: (0, 0)),
            pl.BlockSpec((1, Cout), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((TM, Cout), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((M + pad, Cout), x2.dtype),
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x2, w2, scale.reshape(1, Cout), shift.reshape(1, Cout))
    return out[:M] if pad else out


# ---------------------------------------------------------------------------
# 3x3 family: implicit GEMM over streamed input slabs
# ---------------------------------------------------------------------------

def _conv3x3_kernel(xp_ref, w_ref, scale_ref, shift_ref, o_ref,
                    xbuf, copy_sems, *, stride, th, num_tiles, wo,
                    relu):
    """One program per image. xp_ref is the PADDED `[N, Hp, Wp, Cin]`
    input left in ANY/HBM; the program walks `num_tiles` output-row
    tiles of height `th`, streaming each tile's input slab (the
    `stride*(th-1)+3` rows it reads, halo included) into the
    double-buffered VMEM scratch `xbuf` with the next slab's DMA in
    flight behind the current slab's 9 tap matmuls. The epilogue (BN
    scale/shift + optional ReLU) runs on the fp32 accumulator before
    the single cast + output-tile write."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = pl.program_id(0)
    slab = stride * (th - 1) + 3
    _, wp, cin = xbuf.shape[1:]
    cout = w_ref.shape[3]

    def slab_copy(t, buf):
        return pltpu.make_async_copy(
            xp_ref.at[n, pl.ds(t * th * stride, slab)],
            xbuf.at[buf], copy_sems.at[buf])

    slab_copy(0, 0).start()
    for t in range(num_tiles):                # static unroll (<= 16)
        if t + 1 < num_tiles:
            slab_copy(t + 1, (t + 1) % 2).start()
        slab_copy(t, t % 2).wait()
        x = xbuf[t % 2]                       # [slab, Wp, Cin]
        acc = jnp.zeros((th * wo, cout), jnp.float32)
        for dy in range(3):
            for dx in range(3):
                xs = jax.lax.slice(
                    x, (dy, dx, 0),
                    (dy + stride * (th - 1) + 1,
                     dx + stride * (wo - 1) + 1, cin),
                    (stride, stride, 1))      # [th, Wo, Cin]
                acc = acc + jax.lax.dot_general(
                    xs.reshape(th * wo, cin), w_ref[dy, dx],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
        y = acc * scale_ref[...] + shift_ref[...]
        if relu:
            y = jnp.maximum(y, 0.0)
        o_ref[0, t * th:(t + 1) * th] = \
            y.reshape(th, wo, cout).astype(o_ref.dtype)


def _pick_h_tile(ho=8):
    """Output-row tile: the largest divisor of Ho <= 8 (TH=1 always
    divides, so every Ho has a tile); the kernel's unrolled tile walk
    is bounded by the caller via conv_shapes_supported + the <= 16
    check in the wrapper."""
    for th in (8, 7, 6, 5, 4, 3, 2, 1):
        if ho % th == 0:
            return th
    return 1


def _conv3x3_call(x, w, scale, shift, stride=1, pads=None, relu=True,
                  interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, H, W, Cin = x.shape
    Cout = w.shape[3]
    s = stride
    (pt, pb), (plft, prgt) = pads if pads is not None \
        else ((1, 1), (1, 1))
    xp = jnp.pad(x, ((0, 0), (pt, pb), (plft, prgt), (0, 0)))
    Hp, Wp = H + pt + pb, W + plft + prgt
    Ho = (Hp - 3) // s + 1
    Wo = (Wp - 3) // s + 1
    th = _pick_h_tile(Ho)
    num_tiles = Ho // th
    if num_tiles > 16:                        # unroll-depth bound
        return None
    slab = s * (th - 1) + 3
    if s * (num_tiles - 1) * th + slab > Hp:
        # the last slab would read past the padded input (possible
        # when padding under-covers the kernel); dense handles it
        return None
    out = pl.pallas_call(
        functools.partial(_conv3x3_kernel, stride=s, th=th,
                          num_tiles=num_tiles, wo=Wo, relu=relu),
        grid=(N,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY),
            pl.BlockSpec((3, 3, Cin, Cout), lambda n: (0, 0, 0, 0)),
            pl.BlockSpec((1, Cout), lambda n: (0, 0)),
            pl.BlockSpec((1, Cout), lambda n: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Ho, Wo, Cout),
                               lambda n: (n, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((N, Ho, Wo, Cout), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, slab, Wp, Cin), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(xp, w, scale.reshape(1, Cout), shift.reshape(1, Cout))
    return out


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------

def fused_conv_bn_relu(x, w, scale, shift, stride=1, padding=0,
                       relu=True, interpret=None):
    """Fused conv+BN+ReLU through the Pallas kernels, NHWC layout.

    x `[N, H, W, Cin]`; w `[kh, kw, Cin, Cout]` (HWIO); scale/shift
    `[Cout]` — the BatchNorm affine folded to `y = conv(x)*scale +
    shift` (scale = gamma*rsqrt(var+eps), shift = beta - mean*scale).
    `padding` accepts ints / pairs / (lo, hi) pairs / "SAME"/"VALID".
    Forward-only (no VJP): training runs the dense composition via
    `nn/fused.py`. Off-TPU (or `interpret=True`) the kernels run under
    the Pallas interpreter — the CPU CI path. Raises ValueError on
    shapes `conv_shapes_supported` rejects; resolve the backend first
    (the `nn/fused.py` blocks do) for the clean dense fallback."""
    if interpret is None:
        interpret = not _on_tpu()
    kh, kw = int(w.shape[0]), int(w.shape[1])
    sh, sw = _pair(stride)
    pads = normalize_conv_padding(padding, (kh, kw), (sh, sw),
                                  in_hw=x.shape[1:3])
    if not conv_shapes_supported((kh, kw), (sh, sw), x.shape[3],
                                 w.shape[3], padding=pads):
        raise ValueError(
            f"fused conv kernels do not cover k={kh}x{kw} s={sh}x{sw} "
            f"cin={x.shape[3]} cout={w.shape[3]} pad={pads} — resolve "
            "the backend first and run the dense composition")
    scale = scale.astype(jnp.float32)
    shift = shift.astype(jnp.float32)
    if (kh, kw) == (1, 1):
        N, H, W, Cin = x.shape
        if (sh, sw) != (1, 1):
            x = x[:, ::sh, ::sw]              # exact: SAME k=1 samples
        Ho, Wo = x.shape[1], x.shape[2]
        out2 = _conv1x1_call(x.reshape(N * Ho * Wo, Cin), w[0, 0],
                             scale, shift, relu, interpret)
        out = out2.reshape(N, Ho, Wo, w.shape[3])
    else:
        out = _conv3x3_call(x, w, scale, shift, sh, pads, relu,
                            interpret)
        if out is None:
            raise ValueError(
                "fused 3x3 kernel cannot tile this geometry "
                f"(H={x.shape[1]} pad={pads} stride={sh}) — run the "
                "dense composition")
    CONV_PATH_STATS["pallas"] += 1
    return out


# ---------------------------------------------------------------------------
# tpu-verify: contracts + harvest builders
# ---------------------------------------------------------------------------

# Both kernel families are pure forward programs: nothing donated, no
# collectives at any mp (TPU104 allows zero by default), weights ride
# as traced arguments (TPU102), and every tap/row matmul must
# accumulate fp32 (TPU103 walks the pallas kernel jaxpr — the
# bf16-input harvest shapes give the rule teeth).
register_contract(TraceContract(
    name="conv_bn_relu_1x1",
    declared_at="paddle_tpu/ops/pallas/conv.py"))
register_contract(TraceContract(
    name="conv_bn_relu_3x3",
    declared_at="paddle_tpu/ops/pallas/conv.py"))

#: (contract name, config, kernel, stride, padding, N, H/W, Cin, Cout)
#: — tiny-but-structurally-real instances of every kernel family x
#: stride the suite ships; the asymmetric "SAME" stride-2 3x3 entry
#: covers the halo/padding geometry the bench sweep runs.
CONV_HARVEST_SHAPES = (
    ("conv_bn_relu_1x1", "1x1,s=1", 1, 1, 0, 2, 8, 16, 32),
    ("conv_bn_relu_1x1", "1x1,s=2", 1, 2, 0, 2, 8, 16, 32),
    ("conv_bn_relu_3x3", "3x3,s=1", 3, 1, 1, 2, 8, 16, 16),
    ("conv_bn_relu_3x3", "3x3,s=2", 3, 2, "SAME", 2, 8, 16, 16),
)


def harvest_programs():
    """-> [(name, config, pure_fn, jitted, args)] for the tpu-verify
    harvester: one jitted fused-conv program per CONV_HARVEST_SHAPES
    entry, interpret-mode (the CPU path the gate runs), bf16 inputs so
    TPU103's narrow-operand accumulation check actually bites."""
    out = []
    for name, config, k, s, pad, n, hw, cin, cout in \
            CONV_HARVEST_SHAPES:
        pure = functools.partial(fused_conv_bn_relu, stride=s,
                                 padding=pad, relu=True,
                                 interpret=True)
        args = (jnp.zeros((n, hw, hw, cin), jnp.bfloat16),
                jnp.zeros((k, k, cin, cout), jnp.bfloat16),
                jnp.ones((cout,), jnp.float32),
                jnp.zeros((cout,), jnp.float32))
        out.append((name, config, pure, jax.jit(pure), args))
    return out
